"""Op library aggregator + Tensor method patching.

Reference analog: python/paddle/tensor/__init__.py assembling the tensor API
and python/paddle/base/dygraph/math_op_patch.py monkey-patching operator
methods onto the eager Tensor type.
"""
from __future__ import annotations

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .comparison import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403

from . import creation, math, manipulation, comparison, activation, linalg
from .math import (
    add_, subtract_, multiply_, divide_, clip_, scale_, exp_, sqrt_, rsqrt_,
    reciprocal_, round_, floor_, ceil_, neg_, abs_, tanh_,
)
from .manipulation import _getitem, _setitem
from ..core.tensor import Tensor

# statistics-style ops built on the above
from .stat import *  # noqa: F401,F403
from . import stat

# long-tail surface completion
from .extras import *  # noqa: F401,F403
from . import extras

# inplace variants over the whole op surface
from .inplace import *  # noqa: F401,F403
from . import inplace as _inplace_mod


def _patch_tensor_inplace():
    """Attach every generated inplace/random-fill variant as a method."""
    for name in _inplace_mod.__all__:
        if name.endswith("_"):
            setattr(Tensor, name, getattr(_inplace_mod, name))


def _patch_tensor():
    import numbers

    from . import math as m, manipulation as mp, comparison as c, linalg as la
    from . import activation as act, creation as cr, stat as st

    T = Tensor
    # arithmetic operators
    T.__add__ = lambda s, o: m.add(s, o)
    T.__radd__ = lambda s, o: m.add(s, o)
    T.__sub__ = lambda s, o: m.subtract(s, o)
    T.__rsub__ = lambda s, o: m.subtract(o, s)
    T.__mul__ = lambda s, o: m.multiply(s, o)
    T.__rmul__ = lambda s, o: m.multiply(s, o)
    T.__truediv__ = lambda s, o: m.divide(s, o)
    T.__rtruediv__ = lambda s, o: m.divide(o, s)
    T.__floordiv__ = lambda s, o: m.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: m.floor_divide(o, s)
    T.__mod__ = lambda s, o: m.mod(s, o)
    T.__rmod__ = lambda s, o: m.mod(o, s)
    T.__pow__ = lambda s, o: m.pow(s, o)
    T.__rpow__ = lambda s, o: m.pow(o, s)
    T.__neg__ = lambda s: m.neg(s)
    T.__abs__ = lambda s: m.abs(s)
    T.__matmul__ = lambda s, o: m.matmul(s, o)
    T.__rmatmul__ = lambda s, o: m.matmul(o, s)
    T.__invert__ = lambda s: c.logical_not(s)
    T.__and__ = lambda s, o: c.bitwise_and(s, o)
    T.__or__ = lambda s, o: c.bitwise_or(s, o)
    T.__xor__ = lambda s, o: c.bitwise_xor(s, o)
    # comparisons
    T.__eq__ = lambda s, o: c.equal(s, o)
    T.__ne__ = lambda s, o: c.not_equal(s, o)
    T.__lt__ = lambda s, o: c.less_than(s, o)
    T.__le__ = lambda s, o: c.less_equal(s, o)
    T.__gt__ = lambda s, o: c.greater_than(s, o)
    T.__ge__ = lambda s, o: c.greater_equal(s, o)
    # indexing
    T.__getitem__ = lambda s, idx: _getitem(s, idx)
    T.__setitem__ = lambda s, idx, v: _setitem(s, idx, v)

    # method surface (subset mirroring python/paddle/tensor/__init__.py
    # tensor_method_func list)
    methods = dict(
        add=m.add, subtract=m.subtract, multiply=m.multiply, divide=m.divide,
        floor_divide=m.floor_divide, mod=m.mod, remainder=m.mod, pow=m.pow,
        maximum=m.maximum, minimum=m.minimum, fmax=m.fmax, fmin=m.fmin,
        scale=m.scale, neg=m.neg, abs=m.abs, sqrt=m.sqrt, rsqrt=m.rsqrt,
        square=m.square, exp=m.exp, expm1=m.expm1, log=m.log, log2=m.log2,
        log10=m.log10, log1p=m.log1p, sin=m.sin, cos=m.cos, tan=m.tan,
        asin=m.asin, acos=m.acos, atan=m.atan, sinh=m.sinh, cosh=m.cosh,
        tanh=m.tanh, atan2=m.atan2, floor=m.floor, ceil=m.ceil, round=m.round,
        trunc=m.trunc, frac=m.frac, sign=m.sign, reciprocal=m.reciprocal,
        clip=m.clip, erf=m.erf, erfinv=m.erfinv, lerp=m.lerp, lgamma=m.lgamma,
        digamma=m.digamma, cumsum=m.cumsum, cumprod=m.cumprod,
        logsumexp=m.logsumexp, logcumsumexp=m.logcumsumexp, isnan=m.isnan,
        isinf=m.isinf, isfinite=m.isfinite, nan_to_num=m.nan_to_num, sum=m.sum,
        mean=m.mean, max=m.max, min=m.min, amax=m.amax, amin=m.amin,
        prod=m.prod, all=m.all, any=m.any, matmul=m.matmul, dot=m.dot,
        mm=m.matmul, bmm=m.bmm, inner=m.inner, outer=m.outer, kron=m.kron,
        trace=m.trace, nansum=m.nansum, nanmean=m.nanmean,
        count_nonzero=m.count_nonzero, add_n=m.add_n, stanh=m.stanh,
        rad2deg=m.rad2deg, deg2rad=m.deg2rad, diff=m.diff, angle=m.angle,
        conj=m.conj, real=m.real, imag=m.imag, gcd=m.gcd, lcm=m.lcm,
        divide_no_nan=m.divide_no_nan, cummax=m.cummax, cummin=m.cummin,
        increment=m.increment,
        # inplace
        add_=add_, subtract_=subtract_, multiply_=multiply_, divide_=divide_,
        clip_=clip_, scale_=scale_, exp_=exp_, sqrt_=sqrt_, rsqrt_=rsqrt_,
        reciprocal_=reciprocal_, round_=round_, floor_=floor_, ceil_=ceil_,
        neg_=neg_, abs_=abs_, tanh_=tanh_,
        # manipulation
        reshape=mp.reshape, reshape_=mp.reshape_, transpose=mp.transpose,
        flatten=mp.flatten, squeeze=mp.squeeze, unsqueeze=mp.unsqueeze,
        squeeze_=mp.squeeze_, unsqueeze_=mp.unsqueeze_, concat=mp.concat,
        split=mp.split, chunk=mp.chunk, unbind=mp.unbind, tile=mp.tile,
        expand=mp.expand, expand_as=mp.expand_as, broadcast_to=mp.broadcast_to,
        flip=mp.flip, roll=mp.roll, gather=mp.gather, gather_nd=mp.gather_nd,
        scatter=mp.scatter, scatter_nd_add=mp.scatter_nd_add,
        index_select=mp.index_select, index_sample=mp.index_sample,
        index_add=mp.index_add, take_along_axis=mp.take_along_axis,
        put_along_axis=mp.put_along_axis, masked_select=mp.masked_select,
        masked_fill=mp.masked_fill, where=mp.where, nonzero=mp.nonzero,
        topk=mp.topk, sort=mp.sort, argsort=mp.argsort, argmax=mp.argmax,
        argmin=mp.argmin, unique=mp.unique, numel=mp.numel, pad=mp.pad,
        tensordot=mp.tensordot, moveaxis=mp.moveaxis, swapaxes=mp.swapaxes,
        repeat_interleave=mp.repeat_interleave, diagonal=mp.diagonal,
        as_complex=mp.as_complex, as_real=mp.as_real, rot90=mp.rot90,
        strided_slice=mp.strided_slice, diag_embed=mp.diag_embed,
        # comparison
        equal=c.equal, not_equal=c.not_equal, less_than=c.less_than,
        less_equal=c.less_equal, greater_than=c.greater_than,
        greater_equal=c.greater_equal, equal_all=c.equal_all,
        allclose=c.allclose, isclose=c.isclose, logical_and=c.logical_and,
        logical_or=c.logical_or, logical_xor=c.logical_xor,
        logical_not=c.logical_not, bitwise_and=c.bitwise_and,
        bitwise_or=c.bitwise_or, bitwise_xor=c.bitwise_xor,
        bitwise_not=c.bitwise_not,
        # activation-ish tensor methods
        sigmoid=act.sigmoid, softmax=act.softmax, relu=act.relu,
        # linalg
        norm=la.norm, cholesky=la.cholesky, inverse=la.inv, solve=la.solve,
        matrix_power=la.matrix_power, pinv=la.pinv, det=la.det, cross=la.cross,
        dist=la.dist, histogram=la.histogram, bincount=la.bincount,
        # stat
        std=st.std, var=st.var, median=st.median, quantile=st.quantile,
        nanmedian=st.nanmedian, nanquantile=st.nanquantile, mode=st.mode,
        kthvalue=st.kthvalue,
        # creation-ish
        fill_=cr_fill_, zero_=cr_zero_, uniform_=cr_uniform_,
        normal_=cr_normal_, tril=cr.tril, triu=cr.triu,
    )
    for name, fn in methods.items():
        setattr(T, name, fn)

    @property
    def T_prop(self):
        return mp.transpose(self, list(range(self.ndim))[::-1])

    T.T = T_prop

    @property
    def mT(self):
        return mp.t(self)

    T.mT = mT


def cr_fill_(x, value):
    import jax.numpy as jnp

    x._replace_value(jnp.full(x._value.shape, value, x._value.dtype))
    return x


def cr_zero_(x):
    return cr_fill_(x, 0)


def cr_uniform_(x, min=-1.0, max=1.0, seed=0):
    out = creation.uniform(x.shape, x.dtype, min, max)
    x._replace_value(out._value)
    return x


def cr_normal_(x, mean=0.0, std=1.0):
    out = creation.gaussian(x.shape, mean, std, dtype=x.dtype)
    x._replace_value(out._value)
    return x


_patch_tensor()
_patch_tensor_inplace()
