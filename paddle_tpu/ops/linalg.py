"""Linear algebra ops.

Reference surface: python/paddle/tensor/linalg.py + phi linalg kernels
(cholesky, qr, svd, inverse, solve, eigh, norm, einsum).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, apply
from ._helpers import axis_tuple, binary_args, defprim, ensure_tensor

__all__ = [
    "norm", "vector_norm", "matrix_norm", "cholesky", "qr", "svd", "inv",
    "inverse", "solve", "triangular_solve", "cholesky_solve", "lstsq", "eig",
    "eigh", "eigvals", "eigvalsh", "det", "slogdet", "matrix_power",
    "matrix_rank", "pinv", "cond", "cov", "corrcoef", "histogram", "bincount",
    "einsum", "lu", "householder_product", "multi_dot", "cross", "dist",
]


defprim(
    "p_norm",
    lambda x, *, p, axis, keepdim: _pnorm(x, p, axis, keepdim),
)


def _pnorm(x, p, axis, keepdim):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    if p == 2:
        return jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdim))
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


defprim(
    "fro_norm",
    lambda x, *, axis, keepdim: jnp.sqrt(
        jnp.sum(x * x, axis=axis, keepdims=keepdim)
    ),
)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    if p is None:
        p = "fro" if (axis is None or isinstance(axis, (list, tuple))) else 2
    if isinstance(p, str):
        if p == "fro":
            ax = axis_tuple(axis, x.ndim)
            return apply("fro_norm", x, axis=ax, keepdim=bool(keepdim))
        if p == "nuc":
            return apply("nuc_norm", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim))
        raise ValueError(p)
    ax = axis_tuple(axis, x.ndim)
    if ax is not None and len(ax) == 1:
        ax = ax[0]
    return apply("p_norm", x, p=float(p), axis=ax, keepdim=bool(keepdim))


def _nuc_fwd(x, *, axis, keepdim):
    s = jnp.linalg.svd(x, compute_uv=False)
    return jnp.sum(s, axis=-1, keepdims=keepdim)


defprim("nuc_norm", _nuc_fwd)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=[-2, -1], keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def dist(x, y, p=2, name=None):
    from .math import subtract

    return norm(subtract(x, y), p=float(p))


def _simple(name, fn, multi_out=False, nondiff=False, jittable=True):
    defprim(name, fn, multi_out=multi_out, nondiff=nondiff, jittable=jittable)

    def op(x, name=None):
        return apply(name, ensure_tensor(x))

    op.__name__ = name
    return op


cholesky_ = defprim("cholesky_p", lambda x, *, upper: jnp.linalg.cholesky(x) if not upper else jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2).conj())


def cholesky(x, upper=False, name=None):
    return apply("cholesky_p", ensure_tensor(x), upper=bool(upper))


defprim(
    "qr_p",
    lambda x, *, mode: jnp.linalg.qr(x, mode=mode),
    multi_out=True,
)


def qr(x, mode="reduced", name=None):
    if mode == "r":
        r = jnp.linalg.qr(np.asarray(ensure_tensor(x)._value), mode="r")
        return Tensor._from_value(r)
    return apply("qr_p", ensure_tensor(x), mode=mode)


defprim(
    "svd_p",
    lambda x, *, full_matrices: jnp.linalg.svd(x, full_matrices=full_matrices),
    multi_out=True,
)


def svd(x, full_matrices=False, name=None):
    return apply("svd_p", ensure_tensor(x), full_matrices=bool(full_matrices))


inv = _simple("inverse_p", jnp.linalg.inv)
inverse = inv


def solve(x, y, name=None):
    return apply("solve_p", *binary_args(x, y))


defprim("solve_p", jnp.linalg.solve)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    x, y = binary_args(x, y)
    return apply(
        "triangular_solve_p", x, y, upper=bool(upper), transpose=bool(transpose),
        unitriangular=bool(unitriangular),
    )


defprim(
    "triangular_solve_p",
    lambda x, y, *, upper, transpose, unitriangular: jax.scipy.linalg.solve_triangular(
        x, y, trans=1 if transpose else 0, lower=not upper, unit_diagonal=unitriangular
    ),
)


def cholesky_solve(x, y, upper=False, name=None):
    x, y = binary_args(x, y)
    return apply("cholesky_solve_p", x, y, upper=bool(upper))


defprim(
    "cholesky_solve_p",
    lambda b, chol, *, upper: jax.scipy.linalg.cho_solve((chol, not upper), b),
)


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = binary_args(x, y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._value, y._value, rcond=rcond)
    return (
        Tensor._from_value(sol),
        Tensor._from_value(res),
        Tensor._from_value(rank),
        Tensor._from_value(sv),
    )


det = _simple("det_p", jnp.linalg.det)


def slogdet(x, name=None):
    return apply("slogdet_p", ensure_tensor(x))


defprim(
    "slogdet_p",
    lambda x: tuple(jnp.linalg.slogdet(x)),
    multi_out=True,
)


def eig(x, name=None):
    xv = np.asarray(ensure_tensor(x)._value)
    w, v = np.linalg.eig(xv)
    return Tensor._from_value(jnp.asarray(w)), Tensor._from_value(jnp.asarray(v))


def eigvals(x, name=None):
    xv = np.asarray(ensure_tensor(x)._value)
    return Tensor._from_value(jnp.asarray(np.linalg.eigvals(xv)))


defprim("eigh_p", lambda x, *, UPLO: jnp.linalg.eigh(x, UPLO=UPLO), multi_out=True)


def eigh(x, UPLO="L", name=None):
    return apply("eigh_p", ensure_tensor(x), UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh_p", ensure_tensor(x), UPLO=UPLO)


defprim("eigvalsh_p", lambda x, *, UPLO: jnp.linalg.eigvalsh(x, UPLO=UPLO))


def matrix_power(x, n, name=None):
    return apply("matrix_power_p", ensure_tensor(x), n=int(n))


defprim("matrix_power_p", lambda x, *, n: jnp.linalg.matrix_power(x, n))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor._from_value(
        jnp.linalg.matrix_rank(ensure_tensor(x)._value, rtol=tol)
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv_p", ensure_tensor(x), rcond=float(rcond), hermitian=bool(hermitian))


defprim(
    "pinv_p",
    lambda x, *, rcond, hermitian: jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian),
)


def cond(x, p=None, name=None):
    return Tensor._from_value(jnp.linalg.cond(ensure_tensor(x)._value, p=p))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    x = ensure_tensor(x)
    fw = None if fweights is None else np.asarray(ensure_tensor(fweights)._value)
    aw = None if aweights is None else np.asarray(ensure_tensor(aweights)._value)
    return Tensor._from_value(
        jnp.cov(x._value, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw)
    )


def corrcoef(x, rowvar=True, name=None):
    return Tensor._from_value(jnp.corrcoef(ensure_tensor(x)._value, rowvar=rowvar))


defprim(
    "histogram_p",
    lambda x, *, bins, min, max: jnp.histogram(
        x, bins=bins, range=(min, max) if (min != 0 or max != 0) else None
    )[0].astype(jnp.int64),
    nondiff=True,
)


def histogram(input, bins=100, min=0, max=0, name=None):
    return apply(
        "histogram_p", ensure_tensor(input), bins=int(bins), min=float(min), max=float(max)
    )


defprim(
    "bincount_p",
    lambda x, *, minlength, length: jnp.bincount(x, minlength=minlength, length=length),
    nondiff=True,
)


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    n = int(np.asarray(x._value).max()) + 1 if x.size else 0
    length = max(n, minlength)
    if weights is not None:
        w = ensure_tensor(weights)
        return Tensor._from_value(
            jnp.bincount(x._value, weights=w._value, length=length)
        )
    return apply("bincount_p", x, minlength=int(minlength), length=length)


def einsum(equation, *operands, name=None):
    ts = [ensure_tensor(t) for t in operands]
    name_p = f"einsum_{len(ts)}"
    if name_p not in dispatch.PRIMITIVES:
        dispatch.register_primitive(
            name_p, lambda *xs, equation: jnp.einsum(equation, *xs)
        )
    return apply(name_p, *ts, equation=equation)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(np.asarray(ensure_tensor(x)._value))
    outs = (Tensor._from_value(jnp.asarray(lu_)), Tensor._from_value(jnp.asarray(piv + 1)))
    if get_infos:
        return (*outs, Tensor._from_value(jnp.zeros((), jnp.int32)))
    return outs


def householder_product(x, tau, name=None):
    xv = np.asarray(ensure_tensor(x)._value)
    tv = np.asarray(ensure_tensor(tau)._value)
    import scipy.linalg as sla

    q = sla.lapack.dorgqr(xv.astype(np.float64), tv.astype(np.float64))[0]
    return Tensor._from_value(jnp.asarray(q.astype(xv.dtype)))


def multi_dot(x, name=None):
    from .math import matmul

    out = x[0]
    for m in x[1:]:
        out = matmul(out, m)
    return out


def cross(x, y, axis=9, name=None):
    x, y = binary_args(x, y)
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return apply("cross_p", x, y, axis=int(axis))


defprim("cross_p", lambda x, y, *, axis: jnp.cross(x, y, axis=axis))
