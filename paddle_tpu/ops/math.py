"""Elementwise math, reductions, matmul.

Reference surface: python/paddle/tensor/math.py (~7k LoC of op wrappers over
PHI kernels phi/kernels/elementwise_*, reduce_*, matmul_kernel). Forward =
jnp; backward = explicit VJP where saving-inputs beats recompute, else the
fused jax.vjp fallback (dispatch.py) which XLA DCEs/fuses.

Broadcasting VJP note: binary ops reduce grads back over broadcast axes
(the reference does this inside elementwise_grad kernels).
"""
from __future__ import annotations

import numbers
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, apply
from ._helpers import axis_tuple, binary_args, defprim, ensure_tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "maximum", "minimum", "fmax", "fmin", "atan2",
    "scale", "neg", "abs", "sqrt", "rsqrt", "square", "exp", "expm1", "log",
    "log2", "log10", "log1p", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "asinh", "acosh", "atanh", "floor", "ceil", "round",
    "trunc", "frac", "sign", "reciprocal", "clip", "erf", "erfinv", "lerp",
    "lgamma", "digamma", "cast", "add_n", "cumsum", "cumprod", "cummax", "cummin",
    "logcumsumexp", "isnan", "isinf", "isfinite", "nan_to_num", "sum", "mean",
    "max", "min", "amax", "amin", "prod", "logsumexp", "all", "any", "matmul",
    "dot", "mm", "bmm", "inner", "outer", "addmm", "kron", "trace", "nansum",
    "nanmean", "count_nonzero", "broadcast_shape", "multiply_", "stanh",
    "rad2deg", "deg2rad", "gcd", "lcm", "diff", "angle", "conj", "real", "imag",
    "tanh", "increment", "divide_no_nan",
]


# ---------------------------------------------------------------------------
# broadcasting-aware binary ops with explicit VJPs
# ---------------------------------------------------------------------------
def _unbcast(g, shape):
    """Reduce grad g back to ``shape`` after broadcasting."""
    if tuple(g.shape) == tuple(shape):
        return g
    extra = g.ndim - len(shape)
    if extra > 0:
        g = g.sum(axis=tuple(range(extra)))
    axes = tuple(i for i, s in enumerate(shape) if s == 1 and g.shape[i] != 1)
    if axes:
        g = g.sum(axis=axes, keepdims=True)
    return g.reshape(shape)


def _binary_vjp(dx_fn, dy_fn):
    def vjp(grads_out, saved, **static):
        (g,) = grads_out
        x, y = saved
        gx = _unbcast(dx_fn(g, x, y, **static), x.shape)
        gy = _unbcast(dy_fn(g, x, y, **static), y.shape)
        return gx, gy

    return vjp


_add = defprim(
    "add", jnp.add,
    vjp=_binary_vjp(lambda g, x, y: g, lambda g, x, y: g),
    save=lambda ins, outs: ins,
)
_sub = defprim(
    "subtract", jnp.subtract,
    vjp=_binary_vjp(lambda g, x, y: g, lambda g, x, y: -g),
)
_mul = defprim(
    "multiply", jnp.multiply,
    vjp=_binary_vjp(lambda g, x, y: g * y, lambda g, x, y: g * x),
)
_div = defprim(
    "divide", jnp.divide,
    vjp=_binary_vjp(
        lambda g, x, y: g / y, lambda g, x, y: -g * x / (y * y)
    ),
)
_pow_p = defprim("pow_p", jnp.power)
_maximum = defprim("maximum", jnp.maximum)
_minimum = defprim("minimum", jnp.minimum)
_fmax = defprim("fmax", jnp.fmax)
_fmin = defprim("fmin", jnp.fmin)
_atan2 = defprim("atan2", jnp.arctan2)
_floor_divide = defprim("floor_divide", jnp.floor_divide, nondiff=True)
_mod = defprim("mod", jnp.mod)


def add(x, y, name=None):
    return _add(*binary_args(x, y))


def subtract(x, y, name=None):
    return _sub(*binary_args(x, y))


def multiply(x, y, name=None):
    return _mul(*binary_args(x, y))


def divide(x, y, name=None):
    x, y = binary_args(x, y)
    if np.dtype(x.dtype).kind in "iub":
        x = cast(x, "float32")
        y = cast(y, "float32")
    return _div(x, y)


def floor_divide(x, y, name=None):
    return _floor_divide(*binary_args(x, y))


def mod(x, y, name=None):
    return _mod(*binary_args(x, y))


remainder = mod


def pow(x, y, name=None):
    if isinstance(y, numbers.Number):
        x = ensure_tensor(x)
        return apply("scale_pow", x, exponent=float(y))
    return _pow_p(*binary_args(x, y))


defprim(
    "scale_pow",
    lambda x, *, exponent: jnp.power(x, jnp.asarray(exponent, x.dtype))
    if float(exponent) != int(exponent)
    else jax.lax.integer_pow(x, int(exponent)),
)

float_power = pow


def maximum(x, y, name=None):
    return _maximum(*binary_args(x, y))


def minimum(x, y, name=None):
    return _minimum(*binary_args(x, y))


def fmax(x, y, name=None):
    return _fmax(*binary_args(x, y))


def fmin(x, y, name=None):
    return _fmin(*binary_args(x, y))


def atan2(x, y, name=None):
    return _atan2(*binary_args(x, y))


def divide_no_nan(x, y, name=None):
    x, y = binary_args(x, y)
    return apply("divide_no_nan", x, y)


defprim(
    "divide_no_nan",
    lambda x, y: jnp.where(y == 0, jnp.zeros((), x.dtype), x / jnp.where(y == 0, 1, y)),
)


# ---------------------------------------------------------------------------
# scale — the workhorse for scalar math (reference: phi scale kernel)
# ---------------------------------------------------------------------------
defprim(
    "scale_p",
    lambda x, *, scale, bias, bias_after_scale: (
        x * jnp.asarray(scale, x.dtype) + jnp.asarray(bias, x.dtype)
        if bias_after_scale
        else (x + jnp.asarray(bias, x.dtype)) * jnp.asarray(scale, x.dtype)
    ),
    vjp=lambda grads_out, saved, *, scale, bias, bias_after_scale: (
        grads_out[0] * jnp.asarray(scale, grads_out[0].dtype),
    ),
    save=lambda ins, outs: (),
)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if isinstance(scale, Tensor):
        out = multiply(x, scale)
        if bias:
            out = add(out, bias)
        return out
    return apply(
        "scale_p",
        ensure_tensor(x),
        scale=float(scale),
        bias=float(bias),
        bias_after_scale=bool(bias_after_scale),
    )


def increment(x, value=1.0, name=None):
    out = scale(x, 1.0, float(value))
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


# ---------------------------------------------------------------------------
# unary ops — one-liner prims, fallback VJP (fused/DCEd by XLA)
# ---------------------------------------------------------------------------
def _unary(name, fn, **kw):
    prim = defprim(name, fn, **kw)

    def op(x, name=None):
        return prim(ensure_tensor(x))

    op.__name__ = name
    return op


neg = _unary("neg", jnp.negative)
abs = _unary("abs", jnp.abs)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary(
    "rsqrt",
    jax.lax.rsqrt,
    vjp=lambda g, saved, **kw: (-0.5 * g[0] * saved[0] * saved[0] * saved[0],),
    save=lambda ins, outs: (outs[0],),
)
square = _unary("square", jnp.square)
exp = _unary(
    "exp", jnp.exp,
    vjp=lambda g, saved, **kw: (g[0] * saved[0],),
    save=lambda ins, outs: (outs[0],),
)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
tanh = _unary(
    "tanh", jnp.tanh,
    vjp=lambda g, saved, **kw: (g[0] * (1 - saved[0] * saved[0]),),
    save=lambda ins, outs: (outs[0],),
)
floor = _unary("floor", jnp.floor, nondiff=True)
ceil = _unary("ceil", jnp.ceil, nondiff=True)
round = _unary("round", jnp.round, nondiff=True)
trunc = _unary("trunc", jnp.trunc, nondiff=True)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sign = _unary("sign", jnp.sign, nondiff=True)
reciprocal = _unary("reciprocal", jnp.reciprocal)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
isnan = _unary("isnan", jnp.isnan, nondiff=True)
isinf = _unary("isinf", jnp.isinf, nondiff=True)
isfinite = _unary("isfinite", jnp.isfinite, nondiff=True)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale(tanh(scale(x, scale_a)), scale_b)


defprim(
    "clip_p",
    lambda x, *, min, max: jnp.clip(x, min, max),
)


def clip(x, min=None, max=None, name=None):
    x = ensure_tensor(x)
    if isinstance(min, Tensor) or isinstance(max, Tensor):
        out = x
        if min is not None:
            out = maximum(out, min)
        if max is not None:
            out = minimum(out, max)
        return out
    return apply(
        "clip_p",
        x,
        min=float(min) if min is not None else None,
        max=float(max) if max is not None else None,
    )


defprim("lerp_p", lambda x, y, w: x + w * (y - x))


def lerp(x, y, weight, name=None):
    x, y = binary_args(x, y)
    w = ensure_tensor(weight, dtype=x.dtype) if not isinstance(weight, Tensor) else weight
    return apply("lerp_p", x, y, w)


defprim(
    "nan_to_num_p",
    lambda x, *, nan, posinf, neginf: jnp.nan_to_num(
        x, nan=nan, posinf=posinf, neginf=neginf
    ),
)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(
        "nan_to_num_p", ensure_tensor(x), nan=float(nan),
        posinf=posinf, neginf=neginf,
    )


defprim("cast_p", lambda x, *, dtype: x.astype(jnp.dtype(dtype)))


def cast(x, dtype):
    x = ensure_tensor(x)
    dt = convert_dtype(dtype)
    if np.dtype(x.dtype) == dt:
        return x
    return apply("cast_p", x, dtype=dt.name)


defprim("gcd_p", lambda x, y: jnp.gcd(x, y), nondiff=True)
defprim("lcm_p", lambda x, y: jnp.lcm(x, y), nondiff=True)


def gcd(x, y, name=None):  # noqa: F811
    return apply("gcd_p", *binary_args(x, y))


def lcm(x, y, name=None):
    return apply("lcm_p", *binary_args(x, y))


# ---------------------------------------------------------------------------
# multi-input
# ---------------------------------------------------------------------------
def add_n(inputs, name=None):
    """Reference: phi add_n kernel (sum of N tensors)."""
    if isinstance(inputs, Tensor):
        return inputs
    ts = [ensure_tensor(t) for t in inputs]
    name_p = f"add_n_{len(ts)}"
    from ..core import dispatch

    if name_p not in dispatch.PRIMITIVES:
        import functools as _ft

        dispatch.register_primitive(
            name_p,
            lambda *xs: _ft.reduce(jnp.add, xs),
            vjp=lambda g, saved, **kw: tuple(g[0] for _ in range(saved[0])),
            save=lambda ins, outs: (len(ins),),
        )
    return apply(name_p, *ts)


# ---------------------------------------------------------------------------
# cumulative
# ---------------------------------------------------------------------------
defprim("cumsum_p", lambda x, *, axis: jnp.cumsum(x, axis=axis))
defprim("cumprod_p", lambda x, *, axis: jnp.cumprod(x, axis=axis))
defprim(
    "logcumsumexp_p", lambda x, *, axis: jax.lax.cumlogsumexp(x, axis=axis)
)


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = cast(x, dtype)
    if axis is None:
        from .manipulation import flatten

        return apply("cumsum_p", flatten(x), axis=0)
    return apply("cumsum_p", x, axis=int(axis))


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = cast(x, dtype)
    return apply("cumprod_p", x, axis=int(dim if dim is not None else 0))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        from .manipulation import flatten

        return apply("logcumsumexp_p", flatten(x), axis=0)
    return apply("logcumsumexp_p", x, axis=int(axis))


def _cum_arg(x, axis, op):
    # indices of the running extremum along axis
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis else 1 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)

    def step(carry, xs):
        best, bi = carry
        v, i = xs
        take = op(v, best) == v
        nb = jnp.where(take, v, best)
        nbi = jnp.where(take, i, bi)
        return (nb, nbi), (nb, nbi)

    xm = jnp.moveaxis(x, axis, 0)
    im = jnp.moveaxis(idx, axis, 0)
    init = (xm[0], im[0])
    _, (vals, idxs) = jax.lax.scan(step, init, (xm, im))
    return jnp.moveaxis(idxs, 0, axis).astype(jnp.int64)


defprim(
    "cummax_p",
    lambda x, *, axis: (jax.lax.cummax(x, axis=axis), _cum_arg(x, axis, jnp.maximum)),
    multi_out=True,
)


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    if axis is None:
        from .manipulation import flatten

        x, axis = flatten(x), 0
    return apply("cummax_p", x, axis=int(axis))


defprim(
    "cummin_p",
    lambda x, *, axis: (jax.lax.cummin(x, axis=axis), _cum_arg(x, axis, jnp.minimum)),
    multi_out=True,
)


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    if axis is None:
        from .manipulation import flatten

        x, axis = flatten(x), 0
    return apply("cummin_p", x, axis=int(axis))


# ---------------------------------------------------------------------------
# reductions (reference: phi reduce kernels + spmd reduction rules)
# ---------------------------------------------------------------------------
def _reduce(prim_name, fn, nondiff=False):
    defprim(
        prim_name,
        lambda x, *, axis, keepdim: fn(x, axis=axis, keepdims=keepdim),
        nondiff=nondiff,
    )

    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = ensure_tensor(x)
        if dtype is not None:
            x = cast(x, dtype)
        elif name_needs_upcast(fn, x):
            x = cast(x, "int64")
        return apply(prim_name, x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim))

    return op


def name_needs_upcast(fn, x):
    # paddle sums bool/int32 into int64
    return fn in (jnp.sum, jnp.prod) and np.dtype(x.dtype).kind in "b"


sum = _reduce("reduce_sum", jnp.sum)
mean = _reduce("reduce_mean", jnp.mean)
prod = _reduce("reduce_prod", jnp.prod)
amax = _reduce("reduce_amax", jnp.max)
amin = _reduce("reduce_amin", jnp.min)
nansum = _reduce("reduce_nansum", jnp.nansum)
all = _reduce("reduce_all", jnp.all, nondiff=True)
any = _reduce("reduce_any", jnp.any, nondiff=True)

defprim(
    "reduce_max",
    lambda x, *, axis, keepdim: jnp.max(x, axis=axis, keepdims=keepdim),
)
defprim(
    "reduce_min",
    lambda x, *, axis, keepdim: jnp.min(x, axis=axis, keepdims=keepdim),
)


def max(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("reduce_max", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim))


def min(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("reduce_min", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim))


def nanmean(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "reduce_nanmean", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim)
    )


defprim(
    "reduce_nanmean",
    lambda x, *, axis, keepdim: jnp.nanmean(x, axis=axis, keepdims=keepdim),
)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "count_nonzero_p", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim)
    )


defprim(
    "count_nonzero_p",
    lambda x, *, axis, keepdim: jnp.count_nonzero(x, axis=axis, keepdims=keepdim).astype(
        jnp.int64
    ),
    nondiff=True,
)


defprim(
    "logsumexp_p",
    lambda x, *, axis, keepdim: jax.scipy.special.logsumexp(
        x, axis=axis, keepdims=keepdim
    ),
)


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("logsumexp_p", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim))


# ---------------------------------------------------------------------------
# matmul family — the MXU path. bf16-friendly, explicit VJP avoids saving
# anything beyond the operands (SURVEY §7: keep matmuls large + batched).
# ---------------------------------------------------------------------------
def _matmul_fwd(x, y, *, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


defprim("matmul", _matmul_fwd)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = binary_args(x, y)
    return apply(
        "matmul", x, y, transpose_x=bool(transpose_x), transpose_y=bool(transpose_y)
    )


mm = matmul


def bmm(x, y, name=None):
    return matmul(x, y)


defprim("dot_p", lambda x, y: jnp.sum(x * y, axis=-1))


def dot(x, y, name=None):
    return apply("dot_p", *binary_args(x, y))


def inner(x, y, name=None):
    x, y = binary_args(x, y)
    return apply("inner_p", x, y)


defprim("inner_p", lambda x, y: jnp.inner(x, y))
defprim("outer_p", lambda x, y: jnp.outer(x, y))


def outer(x, y, name=None):
    return apply("outer_p", *binary_args(x, y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return add(scale(input, beta), scale(matmul(x, y), alpha))


defprim("kron_p", lambda x, y: jnp.kron(x, y))


def kron(x, y, name=None):
    return apply("kron_p", *binary_args(x, y))


defprim(
    "trace_p",
    lambda x, *, offset, axis1, axis2: jnp.trace(x, offset, axis1, axis2),
)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        "trace_p", ensure_tensor(x), offset=int(offset), axis1=int(axis1), axis2=int(axis2)
    )


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    arrays = [x]
    if prepend is not None:
        arrays.insert(0, ensure_tensor(prepend))
    if append is not None:
        arrays.append(ensure_tensor(append))
    if len(arrays) > 1:
        from .manipulation import concat

        x = concat(arrays, axis=axis)
    return apply("diff_p", x, n=int(n), axis=int(axis))


defprim("diff_p", lambda x, *, n, axis: jnp.diff(x, n=n, axis=axis))


# ---------------------------------------------------------------------------
# in-place variants (reference: x.add_() etc. — inplace API list in
# python/paddle/tensor/__init__.py). Functional under the hood: compute,
# rebind storage + graph link on the same python object.
# ---------------------------------------------------------------------------
def _make_inplace(op):
    def inplace(x, *args, **kwargs):
        out = op(x, *args, **kwargs)
        x._replace_value(out._value)
        if getattr(out, "_node", None) is not None:
            # grad-tracked: the object adopts the result's graph position
            x._node, x._out_slot = out._node, out._out_slot
            x.stop_gradient = out.stop_gradient
        # else (no_grad / non-differentiable): value-only update — a leaf
        # param updated in place stays a trainable leaf (reference inplace
        # optimizer-update semantics)
        return x

    inplace.__name__ = op.__name__ + "_"
    return inplace


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
clip_ = _make_inplace(clip)
scale_ = _make_inplace(scale)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
neg_ = _make_inplace(neg)
abs_ = _make_inplace(abs)
tanh_ = _make_inplace(tanh)
