"""Long-tail tensor ops completing the reference surface.

Reference: python/paddle/tensor/{math,manipulation,linalg,logic,search}.py —
the remaining public functions beyond the core op files. Most lower to a
single jnp/jax.scipy expression; data-dependent-shape ops document their
eager-only behavior."""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ._helpers import binary_args, defprim, ensure_tensor

__all__ = [
    # elementwise / special functions
    "copysign", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "polygamma", "i0", "i0e", "i1", "i1e", "heaviside", "hypot", "ldexp",
    "frexp", "logaddexp", "logit", "nextafter", "sgn", "signbit", "sinc",
    "isneginf", "isposinf", "isreal", "isin", "bitwise_left_shift",
    "bitwise_right_shift",
    # predicates / conversion
    "is_tensor", "is_complex", "is_floating_point", "is_integer", "rank",
    "tolist",
    # stacking / combination
    "hstack", "vstack", "dstack", "column_stack", "row_stack", "block_diag",
    "broadcast_tensors", "cartesian_prod", "combinations", "vander",
    # scatter / fill variants
    "index_fill", "masked_scatter", "diagonal_scatter", "select_scatter",
    "slice_scatter", "fill_diagonal_tensor",
    # shape / view
    "unflatten", "unfold", "as_strided", "view_as", "multiplex", "mv",
    "take", "shard_index", "renorm",
    # reductions / numerics
    "trapezoid", "cumulative_trapezoid", "cdist", "histogram_bin_edges",
    "histogramdd",
    # linalg extensions
    "matrix_exp", "cholesky_inverse", "lu_unpack", "svd_lowrank",
    "pca_lowrank", "ormqr",
    # random
    "binomial", "poisson", "standard_gamma", "log_normal", "randint_like",
    "top_p_sampling",
    # misc
    "polar",
]


# --------------------------------------------------------------------------
# elementwise / special functions
# --------------------------------------------------------------------------
def _binary(prim_name, fn):
    defprim(prim_name, fn)

    def op(x, y, name=None):
        x, y = binary_args(x, y)
        return apply(prim_name, x, y)

    return op


def _unary(prim_name, fn, **kw):
    defprim(prim_name, fn, **kw)

    def op(x, name=None):
        return apply(prim_name, ensure_tensor(x))

    return op


copysign = _binary("copysign_p", jnp.copysign)
gammaln = _unary("gammaln_p", jax.scipy.special.gammaln)
gammainc = _binary("gammainc_p", jax.scipy.special.gammainc)
gammaincc = _binary("gammaincc_p", jax.scipy.special.gammaincc)
heaviside = _binary("heaviside_p", lambda x, y: jnp.where(
    x < 0.0, 0.0, jnp.where(x > 0.0, 1.0, y)).astype(x.dtype))
hypot = _binary("hypot_p", jnp.hypot)
logaddexp = _binary("logaddexp_p", jnp.logaddexp)
nextafter = _binary("nextafter_p", jnp.nextafter)
sinc = _unary("sinc_p", jnp.sinc)
i0 = _unary("i0_p", lambda x: jax.scipy.special.i0(x))
i0e = _unary("i0e_p", lambda x: jax.scipy.special.i0e(x))
i1 = _unary("i1_p", lambda x: jax.scipy.special.i1(x))
i1e = _unary("i1e_p", lambda x: jax.scipy.special.i1e(x))
signbit = _unary("signbit_p", jnp.signbit, nondiff=True)
isneginf = _unary("isneginf_p", jnp.isneginf, nondiff=True)
isposinf = _unary("isposinf_p", jnp.isposinf, nondiff=True)
isreal = _unary("isreal_p", jnp.isreal, nondiff=True)
bitwise_left_shift = _binary("bitwise_left_shift_p", jnp.left_shift)
bitwise_right_shift = _binary("bitwise_right_shift_p", jnp.right_shift)
sgn = _unary(
    "sgn_p",
    lambda x: jnp.where(
        jnp.abs(x) == 0, 0.0 * x, x / jnp.abs(x)
    ) if jnp.iscomplexobj(x) else jnp.sign(x),
)


def multigammaln(x, p, name=None):
    x = ensure_tensor(x)
    return apply("multigammaln_p", x, p=int(p))


defprim(
    "multigammaln_p",
    lambda x, *, p: p * (p - 1) / 4.0 * _math.log(_math.pi)
    + jnp.sum(
        jax.scipy.special.gammaln(x[..., None] + (1.0 - jnp.arange(1, p + 1)) / 2.0),
        axis=-1,
    ),
)


def polygamma(x, n, name=None):
    if n == 0:
        from .math import digamma

        return digamma(x)
    return apply("polygamma_p", ensure_tensor(x), n=int(n))


defprim("polygamma_p", lambda x, *, n: jax.scipy.special.polygamma(n, x))


def ldexp(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("ldexp_p", x, y)


defprim("ldexp_p", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
defprim("frexp_p", lambda x: jnp.frexp(x), multi_out=True, nondiff=True)


def frexp(x, name=None):
    m, e = apply("frexp_p", ensure_tensor(x))
    from .math import cast

    return m, cast(e, "int32")


def logit(x, eps=None, name=None):
    return apply("logit_p", ensure_tensor(x),
                 eps=None if eps is None else float(eps))


def _logit_fwd(x, *, eps):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


defprim("logit_p", _logit_fwd)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    x, t = ensure_tensor(x), ensure_tensor(test_x)
    return apply("isin_p", x, t, invert=bool(invert))


defprim("isin_p", lambda x, t, *, invert: jnp.isin(x, t, invert=invert),
        nondiff=True)


# --------------------------------------------------------------------------
# predicates / conversion
# --------------------------------------------------------------------------
def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return np.dtype(ensure_tensor(x).dtype).kind == "c"


def is_floating_point(x):
    return np.dtype(ensure_tensor(x).dtype).kind == "f"


def is_integer(x):
    return np.dtype(ensure_tensor(x).dtype).kind in "iu"


def rank(input, name=None):
    from .creation import to_tensor

    return to_tensor(ensure_tensor(input).ndim)


def tolist(x):
    return np.asarray(ensure_tensor(x)._value).tolist()


# --------------------------------------------------------------------------
# stacking / combination
# --------------------------------------------------------------------------
def _multi(prim_name, fn):
    def op(xs, name=None):
        ts = [ensure_tensor(t) for t in xs]
        caller = defprim(f"{prim_name}_{len(ts)}", lambda *arrs: fn(arrs))
        return caller(*ts)

    op.__name__ = prim_name
    return op


hstack = _multi("hstack_p", jnp.hstack)
vstack = _multi("vstack_p", jnp.vstack)
dstack = _multi("dstack_p", jnp.dstack)
column_stack = _multi("column_stack_p", jnp.column_stack)
row_stack = vstack
block_diag = _multi("block_diag_p", lambda arrs: jax.scipy.linalg.block_diag(*arrs))


def broadcast_tensors(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    from .manipulation import broadcast_to

    return [broadcast_to(t, shape) for t in ts]


def cartesian_prod(x, name=None):
    ts = [ensure_tensor(t) for t in x]
    caller = defprim(
        f"cartesian_prod_{len(ts)}",
        lambda *arrs: jnp.stack(
            [g.reshape(-1) for g in jnp.meshgrid(*arrs, indexing="ij")], axis=-1
        ) if len(arrs) > 1 else arrs[0].reshape(-1, 1),
    )
    out = caller(*ts)
    if len(ts) == 1:
        from .manipulation import reshape

        return reshape(out, [-1])
    return out


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor (reference math.py combinations).
    Index set computed host-side (data-independent), gather on device."""
    import itertools

    x = ensure_tensor(x)
    n = x.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), dtype="int64").reshape(-1, r)
    return apply("combinations_p", x, idx_tuple=tuple(map(tuple, idx)))


defprim(
    "combinations_p",
    lambda x, *, idx_tuple: x[jnp.asarray(idx_tuple, jnp.int64).reshape(len(idx_tuple), -1)]
    if len(idx_tuple) else jnp.zeros((0,) + x.shape[1:], x.dtype),
)


def vander(x, n=None, increasing=False, name=None):
    x = ensure_tensor(x)
    return apply("vander_p", x, n=x.shape[0] if n is None else int(n),
                 increasing=bool(increasing))


defprim("vander_p", lambda x, *, n, increasing: jnp.vander(x, n, increasing=increasing))


# --------------------------------------------------------------------------
# scatter / fill variants
# --------------------------------------------------------------------------
def index_fill(x, index, axis, value, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply("index_fill_p", x, index, axis=int(axis), value=float(value))


def _index_fill_fwd(x, index, *, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].set(jnp.asarray(value, x.dtype))
    return jnp.moveaxis(moved, 0, axis)


defprim("index_fill_p", _index_fill_fwd)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions from ``value`` in row-major order (reference
    manipulation.py masked_scatter). Data-dependent placement runs via a
    cumulative index, shape-static."""
    x, mask, value = ensure_tensor(x), ensure_tensor(mask), ensure_tensor(value)
    return apply("masked_scatter_p", x, mask, value)


def _masked_scatter_fwd(x, mask, value):
    mask_b = jnp.broadcast_to(mask, x.shape).reshape(-1)
    flat = x.reshape(-1)
    src = value.reshape(-1)
    pick = jnp.cumsum(mask_b) - 1
    gathered = src[jnp.clip(pick, 0, src.shape[0] - 1)]
    return jnp.where(mask_b, gathered, flat).reshape(x.shape)


defprim("masked_scatter_p", _masked_scatter_fwd)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("diagonal_scatter_p", x, y, offset=int(offset),
                 axis1=int(axis1), axis2=int(axis2))


def _diagonal_scatter_fwd(x, y, *, offset, axis1, axis2):
    moved = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    n, m = moved.shape[-2], moved.shape[-1]
    rows = jnp.arange(max(min(n, m - offset) if offset >= 0 else min(n + offset, m), 0))
    if offset >= 0:
        r, c = rows, rows + offset
    else:
        r, c = rows - offset, rows
    moved = moved.at[..., r, c].set(y)
    return jnp.moveaxis(moved, (-2, -1), (axis1, axis2))


defprim("diagonal_scatter_p", _diagonal_scatter_fwd)


def select_scatter(x, values, axis, index, name=None):
    x, v = ensure_tensor(x), ensure_tensor(values)
    return apply("select_scatter_p", x, v, axis=int(axis), index=int(index))


defprim(
    "select_scatter_p",
    lambda x, v, *, axis, index: jnp.moveaxis(
        jnp.moveaxis(x, axis, 0).at[index].set(v), 0, axis
    ),
)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    x, v = ensure_tensor(x), ensure_tensor(value)
    return apply("slice_scatter_p", x, v, axes=tuple(int(a) for a in axes),
                 starts=tuple(int(s) for s in starts),
                 ends=tuple(int(e) for e in ends),
                 strides=tuple(int(s) for s in strides))


def _slice_scatter_fwd(x, v, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x.at[tuple(idx)].set(v)


defprim("slice_scatter_p", _slice_scatter_fwd)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    return diagonal_scatter(x, y, offset=offset, axis1=dim1, axis2=dim2)


# --------------------------------------------------------------------------
# shape / view
# --------------------------------------------------------------------------
def unflatten(x, axis, shape, name=None):
    x = ensure_tensor(x)
    axis = int(axis) % x.ndim
    new_shape = tuple(x.shape[:axis]) + tuple(int(s) for s in shape) + tuple(
        x.shape[axis + 1:]
    )
    from .manipulation import reshape

    return reshape(x, new_shape)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (reference manipulation.py unfold):
    output appends a window dim of length ``size``."""
    x = ensure_tensor(x)
    return apply("tensor_unfold_p", x, axis=int(axis) % x.ndim, size=int(size),
                 step=int(step))


defprim(
    "tensor_unfold_p",
    lambda x, *, axis, size, step: jnp.moveaxis(
        jnp.moveaxis(x, axis, 0)[
            jnp.arange(0, x.shape[axis] - size + 1, step)[:, None]
            + jnp.arange(size)[None, :]
        ],
        (0, 1), (axis, x.ndim),
    ),
)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view (reference manipulation.py as_strided / kernels/stride).
    XLA has no aliasing views; materialized via a strided gather."""
    x = ensure_tensor(x)
    return apply("as_strided_p", x, shape=tuple(int(s) for s in shape),
                 stride=tuple(int(s) for s in stride), offset=int(offset))


def _as_strided_fwd(x, *, shape, stride, offset):
    flat = x.reshape(-1)
    idx = jnp.asarray(offset)
    for dim, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(dim) * st
    return flat[idx.reshape(shape)]


defprim("as_strided_p", _as_strided_fwd)


def view_as(x, other, name=None):
    from .manipulation import reshape

    return reshape(ensure_tensor(x), tuple(ensure_tensor(other).shape))


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference math.py
    multiplex: out[i] = inputs[index[i]][i])."""
    ts = [ensure_tensor(t) for t in inputs]
    index = ensure_tensor(index)
    caller = defprim(
        f"multiplex_{len(ts)}",
        lambda idx, *arrs: jnp.stack(arrs, 0)[
            idx.reshape(-1).astype(jnp.int64), jnp.arange(arrs[0].shape[0])
        ],
    )
    return caller(index, *ts)


def mv(x, vec, name=None):
    from .math import matmul

    return matmul(x, vec)


def take(x, index, mode="raise", name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"'mode' in 'take' should be 'raise', 'wrap', 'clip', but received {mode}.")
    import jax.core as _jcore

    if mode == "raise" and not isinstance(index._value, _jcore.Tracer):
        idx_np = np.asarray(index._value)
        n = int(np.prod(x.shape)) if x.shape else 1
        if idx_np.size and (idx_np.min() < -n or idx_np.max() >= n):
            raise ValueError(
                f"take index out of range for tensor with {n} elements "
                f"(got min {idx_np.min()}, max {idx_np.max()})"
            )
    return apply("take_p", x, index, mode=mode)


def _take_fwd(x, index, *, mode):
    flat = x.reshape(-1)
    idx = index.astype(jnp.int64)
    n = flat.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    else:  # raise / clip both clamp in-graph (raise validated eagerly)
        idx = jnp.clip(jnp.where(idx < 0, idx + n, idx), 0, n - 1)
    return flat[idx]


defprim("take_p", _take_fwd)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1, name=None):
    """Recompute global label ids for one shard (reference math.py
    shard_index — used by sharded classification heads)."""
    input = ensure_tensor(input)
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"The shard_id({shard_id}) should be in [0, {nshards})"
        )
    return apply("shard_index_p", input, index_num=int(index_num),
                 nshards=int(nshards), shard_id=int(shard_id),
                 ignore_value=int(ignore_value))


def _shard_index_fwd(x, *, index_num, nshards, shard_id, ignore_value):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


defprim("shard_index_p", _shard_index_fwd)


def renorm(x, p, axis, max_norm, name=None):
    x = ensure_tensor(x)
    return apply("renorm_p", x, p=float(p), axis=int(axis) % x.ndim,
                 max_norm=float(max_norm))


def _renorm_fwd(x, *, p, axis, max_norm):
    dims = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
    return x * factor


defprim("renorm_p", _renorm_fwd)


# --------------------------------------------------------------------------
# reductions / numerics
# --------------------------------------------------------------------------
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        return apply("trapezoid_x_p", y, ensure_tensor(x), axis=int(axis))
    return apply("trapezoid_p", y, dx=1.0 if dx is None else float(dx),
                 axis=int(axis))


defprim("trapezoid_p", lambda y, *, dx, axis: jax.scipy.integrate.trapezoid(
    y, dx=dx, axis=axis))
defprim("trapezoid_x_p", lambda y, x, *, axis: jax.scipy.integrate.trapezoid(
    y, x, axis=axis))


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        return apply("cumtrapz_x_p", y, ensure_tensor(x), axis=int(axis))
    return apply("cumtrapz_p", y, dx=1.0 if dx is None else float(dx),
                 axis=int(axis))


def _cumtrapz(y, x=None, dx=1.0, axis=-1):
    ys = jnp.moveaxis(y, axis, -1)
    mids = (ys[..., 1:] + ys[..., :-1]) / 2.0
    if x is not None:
        if x.ndim == 1:
            widths = jnp.diff(x)
        else:
            widths = jnp.diff(jnp.moveaxis(x, axis, -1), axis=-1)
        mids = mids * widths
    else:
        mids = mids * dx
    return jnp.moveaxis(jnp.cumsum(mids, axis=-1), -1, axis)


defprim("cumtrapz_p", lambda y, *, dx, axis: _cumtrapz(y, dx=dx, axis=axis))
defprim("cumtrapz_x_p", lambda y, x, *, axis: _cumtrapz(y, x=x, axis=axis))


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("cdist_p", x, y, p=float(p))


def _cdist_fwd(x, y, *, p):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


defprim("cdist_p", _cdist_fwd)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    input = ensure_tensor(input)
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        arr = np.asarray(input._value)
        lo, hi = float(arr.min()), float(arr.max())
        if lo == hi:
            lo, hi = lo - 0.5, hi + 0.5
    return Tensor._from_value(jnp.linspace(lo, hi, int(bins) + 1))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    """N-d histogram (reference linalg.py histogramdd) — eager numpy."""
    arr = np.asarray(ensure_tensor(x)._value)
    w = None if weights is None else np.asarray(ensure_tensor(weights)._value)
    hist, edges = np.histogramdd(arr, bins=bins, range=ranges, density=density,
                                 weights=w)
    return (Tensor._from_value(jnp.asarray(hist)),
            [Tensor._from_value(jnp.asarray(e)) for e in edges])


# --------------------------------------------------------------------------
# linalg extensions
# --------------------------------------------------------------------------
def matrix_exp(x, name=None):
    return apply("matrix_exp_p", ensure_tensor(x))


defprim("matrix_exp_p", jax.scipy.linalg.expm)


def cholesky_inverse(x, upper=False, name=None):
    return apply("cholesky_inverse_p", ensure_tensor(x), upper=bool(upper))


def _cholesky_inverse_fwd(x, *, upper):
    # inverse of A where x is its Cholesky factor
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    inv_factor = jax.scipy.linalg.solve_triangular(x, eye, lower=not upper)
    return (inv_factor.T @ inv_factor) if not upper else (inv_factor @ inv_factor.T)


defprim("cholesky_inverse_p", _cholesky_inverse_fwd)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack LU factorization results (reference linalg.py lu_unpack)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("lu_unpack_p", x, y)


def _lu_unpack_fwd(lu, pivots):
    n = lu.shape[-2]
    l = jnp.tril(lu, -1) + jnp.eye(n, lu.shape[-1], dtype=lu.dtype)
    l = l[..., :, : min(lu.shape[-2], lu.shape[-1])]
    u = jnp.triu(lu)[..., : min(lu.shape[-2], lu.shape[-1]), :]
    # pivots (1-based sequential swaps) -> permutation matrix
    perm = jnp.arange(n)
    piv = pivots.astype(jnp.int64) - 1

    def body(i, p):
        j = piv[i]
        pi, pj = p[i], p[j]
        return p.at[i].set(pj).at[j].set(pi)

    perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
    pmat = jnp.eye(n, dtype=lu.dtype)[perm].T
    return pmat, l, u


defprim("lu_unpack_p", _lu_unpack_fwd, multi_out=True)


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply ``other`` by the (implicit m×m) orthogonal Q of a QR
    factorization given in Householder form (reference linalg.py ormqr).
    XLA has no ormqr primitive; the reflectors are applied one by one in a
    fori_loop, never materializing Q."""
    return apply("ormqr_p", ensure_tensor(x), ensure_tensor(tau),
                 ensure_tensor(other), left=bool(left),
                 transpose=bool(transpose))


def _ormqr_fwd(a, tau, other, *, left, transpose):
    m, k = a.shape[-2], tau.shape[-1]

    def reflector(i):
        col = a[:, i]
        v = jnp.where(jnp.arange(m) > i, col, 0.0).at[i].set(1.0)
        return v

    def apply_q(mat, trans):
        # Q = H_0 H_1 ... H_{k-1}; Q@x applies reflectors last-to-first,
        # Q^T@x first-to-last (each H_i is symmetric)
        def body(j, acc):
            i = j if trans else k - 1 - j
            v = reflector(i)
            return acc - tau[i] * jnp.outer(v, v @ acc)

        return jax.lax.fori_loop(0, k, body, mat)

    if left:
        return apply_q(other, transpose)
    # x @ op(Q) = (op(Q)^T @ x^T)^T
    return apply_q(other.swapaxes(-1, -2), not transpose).swapaxes(-1, -2)


defprim("ormqr_p", _ormqr_fwd)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD of ``x - M`` (reference linalg.py
    svd_lowrank, Halko et al. subspace iteration)."""
    x = ensure_tensor(x)
    if M is not None:
        x = x - ensure_tensor(M)
    from ..core import generator

    key = Tensor._from_value(generator.next_key())
    return apply("svd_lowrank_p", x, key, q=int(q), niter=int(niter))


def _svd_lowrank_fwd(a, key, *, q, niter):
    m, n = a.shape[-2], a.shape[-1]
    q = min(q, m, n)
    omega = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
    # subspace iteration with QR re-orthonormalization each step (Halko et
    # al. alg. 4.4) — plain power iterations collapse in float32
    qmat, _ = jnp.linalg.qr(a @ omega)
    for _ in range(niter):
        z, _ = jnp.linalg.qr(a.swapaxes(-1, -2) @ qmat)
        qmat, _ = jnp.linalg.qr(a @ z)
    b = qmat.swapaxes(-1, -2) @ a
    u, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u, s, vh.swapaxes(-1, -2)


defprim("svd_lowrank_p", _svd_lowrank_fwd, multi_out=True)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = ensure_tensor(x)
    if q is None:
        q = min(6, x.shape[-2], x.shape[-1])
    if center:
        from .math import mean

        x = x - mean(x, axis=-2, keepdim=True)
    return svd_lowrank(x, q=q, niter=niter)


# --------------------------------------------------------------------------
# random
# --------------------------------------------------------------------------
def _key_tensor():
    from ..core import generator

    return Tensor._from_value(generator.next_key())


def binomial(count, prob, name=None):
    count, prob = binary_args(count, prob)
    return apply("binomial_sample_p", _key_tensor(), count, prob)


defprim(
    "binomial_sample_p",
    lambda key, n, p: jax.random.binomial(key, n, p).astype(jnp.int64),
    nondiff=True, jittable=False,
)


def poisson(x, name=None):
    x = ensure_tensor(x)
    return apply("poisson_sample_p", _key_tensor(), x)


defprim(
    "poisson_sample_p",
    lambda key, lam: jax.random.poisson(key, lam).astype(lam.dtype),
    nondiff=True,
)


def standard_gamma(x, name=None):
    x = ensure_tensor(x)
    return apply("standard_gamma_p", _key_tensor(), x)


defprim(
    "standard_gamma_p",
    lambda key, alpha: jax.random.gamma(key, alpha, dtype=alpha.dtype),
)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    from .math import exp

    from .creation import normal

    return exp(normal(float(mean), float(std), shape))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    from .creation import randint
    from .math import cast

    if high is None:
        low, high = 0, low
    target = dtype or np.dtype(x.dtype).name
    out = randint(low, high, tuple(x.shape), "int64")
    return cast(out, target)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (reference math.py
    top_p_sampling): sample from the smallest prefix of the sorted
    distribution whose mass exceeds p."""
    x, ps = ensure_tensor(x), ensure_tensor(ps)
    return apply("top_p_sampling_p", _key_tensor(), x, ps)


def _top_p_fwd(key, probs, ps):
    sort_idx = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    keep = cum - sorted_p <= ps[..., None]     # always keep the top token
    masked = jnp.where(keep, sorted_p, 0.0)
    masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
    draw = jax.random.categorical(key, jnp.log(jnp.maximum(masked, 1e-30)), axis=-1)
    ids = jnp.take_along_axis(sort_idx, draw[..., None], axis=-1)
    scores = jnp.take_along_axis(probs, ids, axis=-1)
    return scores, ids.astype(jnp.int64)


defprim("top_p_sampling_p", _top_p_fwd, multi_out=True, nondiff=True)


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------
def polar(abs, angle, name=None):
    abs_t, angle_t = binary_args(abs, angle)
    return apply("polar_p", abs_t, angle_t)


defprim("polar_p", lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)))


def pdist(x, p=2.0, name=None):
    """Condensed pairwise p-norm distances of the rows of x [N, D] →
    [N*(N-1)/2] (reference: tensor/linalg.py pdist)."""
    x = ensure_tensor(x)
    return apply("pdist_p", x, p=float(p))


def _pdist_fwd(x, *, p):
    n = x.shape[0]
    diff = x[:, None, :] - x[None, :, :]
    if p == 2.0:
        d = jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 0.0)
    elif p == 0.0:
        d = jnp.sum(diff != 0, axis=-1).astype(x.dtype)
    elif p == float("inf"):
        d = jnp.max(jnp.abs(diff), axis=-1)
    else:
        d = jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    iu, ju = jnp.triu_indices(n, k=1)
    return d[iu, ju]


defprim("pdist_p", _pdist_fwd)


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (reference: tensor/math.py reduce_as —
    the broadcast-inverse reduction)."""
    x = ensure_tensor(x)
    target = ensure_tensor(target)
    return apply("reduce_as_p", x, target)


def _reduce_as_fwd(x, target):
    tshape = target.shape
    ndiff = x.ndim - len(tshape)
    axes = tuple(range(ndiff)) + tuple(
        i + ndiff for i, s in enumerate(tshape) if s == 1 and x.shape[i + ndiff] != 1
    )
    out = jnp.sum(x, axis=axes, keepdims=False)
    return out.reshape(tshape)


defprim("reduce_as_p", _reduce_as_fwd)

__all__.extend(["pdist", "reduce_as"])
