"""Inplace op surface completion.

Reference: python/paddle/tensor/__init__.py tensor_method_func — every
``op_`` name rebinds the same python Tensor to the op's result (storage
swap; the graph link moves with it). Random fills (normal_/bernoulli_/
cauchy_/geometric_/log_normal_/exponential_) sample through the framework
generator so seeding matches the functional ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import ensure_tensor
from .math import _make_inplace

__all__ = []


def _export(name, fn):
    fn.__name__ = name
    globals()[name] = fn
    __all__.append(name)
    return fn


def _build_inplace_variants():
    from . import activation as act
    from . import comparison as c
    from . import creation as cr
    from . import extras as ex
    from . import manipulation as mp
    from . import math as m

    sources = {}
    for mod in (m, mp, c, act, ex, cr):
        for n in dir(mod):
            if not n.startswith("_") and callable(getattr(mod, n)):
                sources.setdefault(n, getattr(mod, n))

    names = [
        "addmm", "t", "cumsum", "cumprod", "logit", "equal", "cos",
        "tan", "logical_and", "logical_or", "logical_xor", "logical_not",
        "less_than", "less_equal", "greater_than", "greater_equal",
        "not_equal", "floor_divide", "remainder", "mod", "floor_mod",
        "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "triu",
        "tril", "sin", "pow", "acos", "asin", "atan", "expm1", "sinh",
        "cosh", "sinc", "lgamma", "gammaincc", "gammainc", "square",
        "gammaln", "gcd", "lcm", "cast", "erf", "transpose", "digamma",
        "erfinv", "log", "log2", "log10", "log1p", "trunc", "frac",
        "nan_to_num", "fill_diagonal", "lerp", "put_along_axis",
        "index_put", "index_fill", "renorm", "copysign", "hypot",
        "ldexp", "i0", "atanh", "asinh", "acosh", "flatten", "scatter",
        "index_add", "multigammaln", "polygamma", "bitwise_left_shift",
        "bitwise_right_shift", "masked_fill", "masked_scatter",
    ]
    for n in names:
        base = sources.get(n)
        if base is None:
            continue
        _export(n + "_", _make_inplace(base))


_build_inplace_variants()

def where_(condition, x, y=None, name=None):
    """Reference where_ inplaces X (the second argument), not the
    condition — generated _make_inplace would mutate the wrong operand."""
    from .manipulation import where

    x = ensure_tensor(x)
    out = where(condition, x, y)
    x._replace_value(out._value)
    if getattr(out, "_node", None) is not None:
        x._node, x._out_slot = out._node, out._out_slot
        x.stop_gradient = out.stop_gradient
    return x


__all__.append("where_")


# floor_mod is an alias of mod in the reference op_compat table
from .math import mod as floor_mod  # noqa: E402

floor_mod_ = _make_inplace(floor_mod)
floor_mod_.__name__ = "floor_mod_"
__all__.extend(["floor_mod", "floor_mod_"])


# ---------------------------------------------------------------------------
# inplace random fills — reference: tensor/random.py (Tensor.normal_,
# bernoulli_, cauchy_, geometric_, log_normal_, exponential_, uniform_)
# ---------------------------------------------------------------------------
def _next_key():
    from ..core import generator

    return generator.next_key("local_seed")


def _fill(x: Tensor, sample) -> Tensor:
    x._replace_value(sample.astype(x._value.dtype))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    # routes through creation.gaussian so the sample stream matches the
    # functional paddle.normal
    from .creation import gaussian

    x = ensure_tensor(x)
    return _fill(x, gaussian(x.shape, mean, std, dtype=x.dtype)._value)


def log_normal_(x, mean=1.0, std=2.0, name=None):
    x = ensure_tensor(x)
    s = jnp.exp(jax.random.normal(_next_key(), x._value.shape) * std + mean)
    return _fill(x, s)


def bernoulli_(x, p=0.5, name=None):
    x = ensure_tensor(x)
    s = jax.random.bernoulli(_next_key(), p, x._value.shape)
    return _fill(x, s)


def cauchy_(x, loc=0, scale=1, name=None):
    x = ensure_tensor(x)
    u = jax.random.uniform(_next_key(), x._value.shape,
                           minval=1e-7, maxval=1.0 - 1e-7)
    s = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
    return _fill(x, s)


def geometric_(x, probs, name=None):
    x = ensure_tensor(x)
    u = jax.random.uniform(_next_key(), x._value.shape,
                           minval=1e-7, maxval=1.0 - 1e-7)
    # number of Bernoulli(p) trials to first success (support 1, 2, ...)
    s = jnp.ceil(jnp.log(u) / jnp.log1p(-jnp.asarray(probs, jnp.float32)))
    return _fill(x, s)


def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    u = jax.random.uniform(_next_key(), x._value.shape,
                           minval=1e-7, maxval=1.0)
    return _fill(x, -jnp.log(u) / lam)


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    from .creation import uniform

    x = ensure_tensor(x)
    return _fill(x, uniform(x.shape, x.dtype, min, max)._value)


for _n in ("normal_", "log_normal_", "bernoulli_", "cauchy_", "geometric_",
           "exponential_", "uniform_"):
    __all__.append(_n)
