"""Tensor creation ops.

Reference surface: python/paddle/tensor/creation.py (to_tensor, zeros, ones,
full, arange, eye, linspace, tril/triu, meshgrid, diag, ...) and
python/paddle/tensor/random.py (rand/randn/uniform/normal/randint/randperm/
bernoulli/multinomial). Random ops take an explicit threefry key input
(core/generator.py) so VJP-fallback recompute and jit capture stay
deterministic — the Philox seed+offset analog of phi/core/generator.h.
"""
from __future__ import annotations

import numbers
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import generator
from ..core.dtype import convert_dtype
from ..core.flags import get_flag
from ..core.tensor import Tensor, apply
from ._helpers import defprim, ensure_tensor

__all__ = [
    "create_tensor",
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "assign", "clone",
    "rand", "randn", "uniform", "normal", "standard_normal", "gaussian",
    "randint", "randperm", "bernoulli", "multinomial", "one_hot", "tril_indices",
    "triu_indices", "complex",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = np.dtype(default or get_flag("default_dtype"))
    return d


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity (creation.py:to_tensor)."""
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None else data.clone()
        t.stop_gradient = stop_gradient
        return t
    t = ensure_tensor(data, dtype)
    if place is not None:
        t = t.to(place)
    t.stop_gradient = stop_gradient
    return t


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor._from_value(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor._from_value(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, (bool, np.bool_)):
            dtype = "bool"
        elif isinstance(fill_value, numbers.Integral):
            dtype = "int64"
        else:
            dtype = get_flag("default_dtype")
    return Tensor._from_value(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor._from_value(jnp.zeros(x.shape, _dt(dtype, x.dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor._from_value(jnp.ones(x.shape, _dt(dtype, x.dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor._from_value(jnp.full(x.shape, fill_value, _dt(dtype, x.dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(end, Tensor):
        end = end.item()
    if isinstance(step, Tensor):
        step = step.item()
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, numbers.Integral) for v in (start, end, step))
            else get_flag("default_dtype")
        )
    return Tensor._from_value(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(stop, Tensor):
        stop = stop.item()
    if isinstance(num, Tensor):
        num = int(num.item())
    return Tensor._from_value(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor._from_value(
        jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor._from_value(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


_tril = defprim("tril", lambda x, *, diagonal: jnp.tril(x, diagonal))
_triu = defprim("triu", lambda x, *, diagonal: jnp.triu(x, diagonal))


def tril(x, diagonal: int = 0, name=None) -> Tensor:
    return _tril(ensure_tensor(x), diagonal=int(diagonal))


def triu(x, diagonal: int = 0, name=None) -> Tensor:
    return _triu(ensure_tensor(x), diagonal=int(diagonal))


_diag = defprim("diag", lambda x, *, offset: jnp.diag(x, offset))


def diag(x, offset: int = 0, padding_value: float = 0, name=None) -> Tensor:
    x = ensure_tensor(x)
    out = _diag(x, offset=int(offset))
    if padding_value != 0 and x.ndim == 1:
        from .math import add, multiply

        n = out.shape[0]
        mask = jnp.eye(n, k=offset, dtype=bool)
        fill = jnp.where(mask, 0.0, padding_value).astype(out.dtype)
        return add(out, Tensor._from_value(fill))
    return out


def diagflat(x, offset: int = 0, name=None) -> Tensor:
    x = ensure_tensor(x)
    from .manipulation import flatten

    return diag(flatten(x), offset)


def meshgrid(*args, name=None):
    args = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[a._value for a in args], indexing="ij")
    return [Tensor._from_value(o) for o in outs]


_assign = defprim("assign", lambda x: jnp.asarray(x))


def assign(x, output=None) -> Tensor:
    x = ensure_tensor(x)
    out = _assign(x)
    if output is not None:
        output._replace_value(out._value)
        output._node, output._out_slot = out._node, out._out_slot
        output.stop_gradient = out.stop_gradient
        return output
    return out


clone = assign


def complex(real, imag, name=None) -> Tensor:
    from ._helpers import binary_args

    real, imag = binary_args(real, imag)
    return apply("complex_", real, imag)


defprim("complex_", lambda r, i: jax.lax.complex(r, i))


# ---------------------------------------------------------------------------
# random creation (keys from core/generator — RNGStatesTracker streams)
# ---------------------------------------------------------------------------
def _key_tensor(name="global_seed") -> Tensor:
    return Tensor._from_value(generator.next_key(name))


defprim(
    "uniform_p",
    lambda key, *, shape, dtype, min, max: jax.random.uniform(
        key, shape, jnp.dtype(dtype), min, max
    ),
    nondiff=True,
)
defprim(
    "normal_p",
    lambda key, *, shape, dtype, mean, std: mean
    + std * jax.random.normal(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
defprim(
    "randint_p",
    lambda key, *, low, high, shape, dtype: jax.random.randint(
        key, shape, low, high, jnp.dtype(dtype)
    ),
    nondiff=True,
)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    dt = _dt(dtype)
    return apply(
        "uniform_p",
        _key_tensor(),
        shape=_shape(shape),
        dtype=dt.name,
        min=float(min),
        max=float(max),
    )


def rand(shape, dtype=None, name=None) -> Tensor:
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean) if not isinstance(mean, Tensor) else mean
        shape_ = m.shape if shape is None else _shape(shape)
        noise = apply(
            "normal_p", _key_tensor(), shape=tuple(shape_),
            dtype=np.dtype(m.dtype).name, mean=0.0, std=1.0,
        )
        from .math import add, multiply

        return add(multiply(noise, ensure_tensor(std)), m)
    dt = _dt(None)
    return apply(
        "normal_p", _key_tensor(), shape=_shape(shape), dtype=dt.name,
        mean=float(mean), std=float(std),
    )


def randn(shape, dtype=None, name=None) -> Tensor:
    dt = _dt(dtype)
    return apply(
        "normal_p", _key_tensor(), shape=_shape(shape), dtype=dt.name,
        mean=0.0, std=1.0,
    )


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    dt = _dt(dtype)
    return apply(
        "normal_p", _key_tensor(), shape=_shape(shape), dtype=dt.name,
        mean=float(mean), std=float(std),
    )


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return apply(
        "randint_p", _key_tensor(), low=int(low), high=int(high),
        shape=_shape(shape), dtype=np.dtype(convert_dtype(dtype)).name,
    )


defprim(
    "randperm_p",
    lambda key, *, n, dtype: jax.random.permutation(key, n).astype(jnp.dtype(dtype)),
    nondiff=True,
)


def randperm(n, dtype="int64", name=None) -> Tensor:
    return apply("randperm_p", _key_tensor(), n=int(n), dtype=np.dtype(convert_dtype(dtype)).name)


defprim(
    "bernoulli_p",
    lambda x, key: jax.random.bernoulli(key, x).astype(x.dtype),
    nondiff=True,
)


def bernoulli(x, name=None) -> Tensor:
    return apply("bernoulli_p", ensure_tensor(x), _key_tensor())


defprim(
    "multinomial_p",
    lambda x, key, *, num_samples, replacement: jax.random.categorical(
        key, jnp.log(jnp.maximum(x, 1e-38)), axis=-1,
        shape=(*x.shape[:-1], num_samples) if x.ndim > 1 else (num_samples,),
    ).astype(jnp.int64),
    nondiff=True,
)


def _multinomial_noreplace_fwd(x, key, *, num_samples):
    # without-replacement via Gumbel top-k (jax idiom)
    g = jax.random.gumbel(key, x.shape, jnp.float32)
    scores = jnp.log(jnp.maximum(x.astype(jnp.float32), 1e-38)) + g
    _, idx = jax.lax.top_k(scores, num_samples)
    return idx.astype(jnp.int64)


defprim("multinomial_noreplace_p", _multinomial_noreplace_fwd, nondiff=True)


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    if not replacement and num_samples > 1:
        return apply(
            "multinomial_noreplace_p", x, _key_tensor(), num_samples=int(num_samples)
        )
    return apply(
        "multinomial_p", x, _key_tensor(),
        num_samples=int(num_samples), replacement=bool(replacement),
    )


defprim(
    "one_hot_p",
    lambda x, *, num_classes: jax.nn.one_hot(x, num_classes, dtype=jnp.float32),
    nondiff=True,
)


def one_hot(x, num_classes, name=None) -> Tensor:
    return apply("one_hot_p", ensure_tensor(x), num_classes=int(num_classes))


def tril_indices(row, col, offset=0, dtype="int64") -> Tensor:
    r, c = np.tril_indices(row, offset, col)
    return Tensor._from_value(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64") -> Tensor:
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor._from_value(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def create_tensor(dtype, name=None, persistable=False):
    """Reference: tensor/creation.py create_tensor — an empty typed tensor
    placeholder (static-era API; eager form is a 0-size tensor)."""
    from ..core.dtype import convert_dtype

    t = Tensor(jnp.zeros((0,), convert_dtype(dtype)))
    t.persistable = persistable
    return t
