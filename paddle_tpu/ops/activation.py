"""Activation ops.

Reference surface: python/paddle/nn/functional/activation.py over phi
activation kernels. Explicit VJPs save outputs where cheaper (sigmoid, tanh
pattern); the rest use the fused fallback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ._helpers import defprim, ensure_tensor

__all__ = [
    "relu", "relu6", "relu_", "leaky_relu", "elu", "selu", "celu", "gelu",
    "silu", "swish", "mish", "sigmoid", "hardsigmoid", "hardswish", "hardtanh",
    "hardshrink", "softshrink", "tanhshrink", "softplus", "softsign",
    "log_sigmoid", "softmax", "log_softmax", "prelu", "glu", "maxout",
    "thresholded_relu", "rrelu", "gumbel_softmax",
]

defprim(
    "relu",
    lambda x: jnp.maximum(x, 0),
    vjp=lambda g, saved, **kw: (jnp.where(saved[0] > 0, g[0], 0),),
    save=lambda ins, outs: (outs[0],),
)
defprim(
    "sigmoid",
    jax.nn.sigmoid,
    vjp=lambda g, saved, **kw: (g[0] * saved[0] * (1 - saved[0]),),
    save=lambda ins, outs: (outs[0],),
)
defprim("relu6", lambda x: jnp.clip(x, 0, 6))
defprim("leaky_relu_p", lambda x, *, slope: jax.nn.leaky_relu(x, slope))
defprim("elu_p", lambda x, *, alpha: jax.nn.elu(x, alpha))
defprim("selu_p", lambda x, *, scale, alpha: scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)))
defprim("celu_p", lambda x, *, alpha: jax.nn.celu(x, alpha))
defprim(
    "gelu_p", lambda x, *, approximate: jax.nn.gelu(x, approximate=approximate)
)
defprim("silu", jax.nn.silu)
defprim("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
defprim("hardsigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
defprim("hardswish", lambda x: x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
defprim("hardtanh_p", lambda x, *, min, max: jnp.clip(x, min, max))
defprim(
    "hardshrink_p",
    lambda x, *, threshold: jnp.where(jnp.abs(x) > threshold, x, 0.0),
)
defprim(
    "softshrink_p",
    lambda x, *, threshold: jnp.where(
        x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0)
    ),
)
defprim("tanhshrink", lambda x: x - jnp.tanh(x))
defprim(
    "softplus_p",
    lambda x, *, beta, threshold: jnp.where(
        x * beta > threshold, x, jax.nn.softplus(x * beta) / beta
    ),
)
defprim("softsign", jax.nn.soft_sign)
defprim("log_sigmoid", jax.nn.log_sigmoid)
defprim(
    "softmax_p",
    lambda x, *, axis: jax.nn.softmax(x, axis=axis),
    vjp=lambda g, saved, *, axis: (
        saved[0] * (g[0] - jnp.sum(g[0] * saved[0], axis=axis, keepdims=True)),
    ),
    save=lambda ins, outs: (outs[0],),
)
defprim("log_softmax_p", lambda x, *, axis: jax.nn.log_softmax(x, axis=axis))
defprim(
    "thresholded_relu_p",
    lambda x, *, threshold, value: jnp.where(x > threshold, x, value),
)
defprim("prelu_p", lambda x, w, *, axis_shape: jnp.where(x > 0, x, x * w.reshape(axis_shape)))


def relu(x, name=None):
    return apply("relu", ensure_tensor(x))


def relu_(x, name=None):
    out = relu(x)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


def relu6(x, name=None):
    return apply("relu6", ensure_tensor(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu_p", ensure_tensor(x), slope=float(negative_slope))


def elu(x, alpha=1.0, name=None):
    return apply("elu_p", ensure_tensor(x), alpha=float(alpha))


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply("selu_p", ensure_tensor(x), scale=float(scale), alpha=float(alpha))


def celu(x, alpha=1.0, name=None):
    return apply("celu_p", ensure_tensor(x), alpha=float(alpha))


def gelu(x, approximate=False, name=None):
    return apply("gelu_p", ensure_tensor(x), approximate=bool(approximate))


def silu(x, name=None):
    return apply("silu", ensure_tensor(x))


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return apply("mish", ensure_tensor(x))


def sigmoid(x, name=None):
    return apply("sigmoid", ensure_tensor(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid", ensure_tensor(x))


def hardswish(x, name=None):
    return apply("hardswish", ensure_tensor(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh_p", ensure_tensor(x), min=float(min), max=float(max))


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink_p", ensure_tensor(x), threshold=float(threshold))


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink_p", ensure_tensor(x), threshold=float(threshold))


def tanhshrink(x, name=None):
    return apply("tanhshrink", ensure_tensor(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        "softplus_p", ensure_tensor(x), beta=float(beta), threshold=float(threshold)
    )


def softsign(x, name=None):
    return apply("softsign", ensure_tensor(x))


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", ensure_tensor(x))


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        from .math import cast

        x = cast(x, dtype)
    return apply("softmax_p", x, axis=int(axis) % x.ndim - x.ndim)


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        from .math import cast

        x = cast(x, dtype)
    return apply("log_softmax_p", x, axis=int(axis) % x.ndim - x.ndim)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(
        "thresholded_relu_p", ensure_tensor(x), threshold=float(threshold),
        value=float(value),
    )


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    n = weight.size
    shape = [1] * x.ndim
    if n > 1:
        ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape[ch_axis] = n
    return apply("prelu_p", x, weight, axis_shape=tuple(shape))


def glu(x, axis=-1, name=None):
    from .manipulation import split

    a, b = split(ensure_tensor(x), 2, axis)
    from .math import multiply

    return multiply(a, sigmoid(b))


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)
    axis = axis % x.ndim
    c = x.shape[axis]
    return apply("maxout_p", x, groups=int(groups), axis=int(axis), channels=c)


def _maxout_fwd(x, *, groups, axis, channels):
    shape = list(x.shape)
    shape[axis : axis + 1] = [channels // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


defprim("maxout_p", _maxout_fwd)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    if not training:
        return leaky_relu(x, (lower + upper) / 2)
    from ..core import generator

    key = Tensor._from_value(generator.next_key("local_seed"))
    return apply("rrelu_p", ensure_tensor(x), key, lower=float(lower), upper=float(upper))


defprim(
    "rrelu_p",
    lambda x, key, *, lower, upper: jnp.where(
        x >= 0, x, x * jax.random.uniform(key, x.shape, x.dtype, lower, upper)
    ),
)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core import generator

    key = Tensor._from_value(generator.next_key())
    return apply(
        "gumbel_softmax_p", ensure_tensor(x), key,
        temperature=float(temperature), hard=bool(hard), axis=int(axis),
    )


def _gumbel_softmax_fwd(x, key, *, temperature, hard, axis):
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y).at[
            tuple(
                idx if d == (axis % y.ndim) else jnp.indices(idx.shape)[d]
                for d in range(y.ndim)
            )
        ].set(1.0)
        y = jax.lax.stop_gradient(onehot - y) + y
    return y


defprim("gumbel_softmax_p", _gumbel_softmax_fwd)
