"""Comparison, logical, and bitwise ops.

Reference surface: python/paddle/tensor/logic.py + phi compare/bitwise kernels.
All nondiff (bool/int outputs).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ._helpers import binary_args, defprim, ensure_tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "is_empty", "searchsorted", "bucketize",
]


def _make_cmp(pub_name, prim_name, fn):
    defprim(prim_name, fn, nondiff=True)

    def op(x, y, name=None):
        return apply(prim_name, *binary_args(x, y))

    op.__name__ = pub_name
    return op


equal = _make_cmp("equal", "equal_p", jnp.equal)
not_equal = _make_cmp("not_equal", "not_equal_p", jnp.not_equal)
less_than = _make_cmp("less_than", "less_than_p", jnp.less)
less_equal = _make_cmp("less_equal", "less_equal_p", jnp.less_equal)
greater_than = _make_cmp("greater_than", "greater_than_p", jnp.greater)
greater_equal = _make_cmp("greater_equal", "greater_equal_p", jnp.greater_equal)
logical_and = _make_cmp("logical_and", "logical_and_p", jnp.logical_and)
logical_or = _make_cmp("logical_or", "logical_or_p", jnp.logical_or)
logical_xor = _make_cmp("logical_xor", "logical_xor_p", jnp.logical_xor)
bitwise_and = _make_cmp("bitwise_and", "bitwise_and_p", jnp.bitwise_and)
bitwise_or = _make_cmp("bitwise_or", "bitwise_or_p", jnp.bitwise_or)
bitwise_xor = _make_cmp("bitwise_xor", "bitwise_xor_p", jnp.bitwise_xor)

defprim("logical_not_p", jnp.logical_not, nondiff=True)
defprim("bitwise_not_p", jnp.bitwise_not, nondiff=True)


def logical_not(x, name=None):
    return apply("logical_not_p", ensure_tensor(x))


def bitwise_not(x, name=None):
    return apply("bitwise_not_p", ensure_tensor(x))


defprim("equal_all_p", lambda x, y: jnp.array_equal(x, y), nondiff=True)


def equal_all(x, y, name=None):
    return apply("equal_all_p", *binary_args(x, y))


defprim(
    "isclose_p",
    lambda x, y, *, rtol, atol, equal_nan: jnp.isclose(
        x, y, rtol=rtol, atol=atol, equal_nan=equal_nan
    ),
    nondiff=True,
)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = binary_args(x, y)
    return apply(
        "isclose_p", x, y, rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan)
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = binary_args(x, y)
    return apply(
        "allclose_p", x, y, rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan)
    )


defprim(
    "allclose_p",
    lambda x, y, *, rtol, atol, equal_nan: jnp.allclose(
        x, y, rtol=rtol, atol=atol, equal_nan=equal_nan
    ),
    nondiff=True,
)


def is_empty(x, name=None):
    return Tensor._from_value(jnp.asarray(ensure_tensor(x).size == 0))


defprim(
    "searchsorted_p",
    lambda a, v, *, right: jnp.searchsorted(
        a, v, side="right" if right else "left"
    ).astype(jnp.int64),
    nondiff=True,
)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = apply(
        "searchsorted_p",
        ensure_tensor(sorted_sequence),
        ensure_tensor(values),
        right=bool(right),
    )
    return out.astype("int32") if out_int32 else out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
