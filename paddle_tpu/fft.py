"""Discrete Fourier transforms — ``paddle.fft`` parity.

Reference surface: python/paddle/fft.py (fft/ifft/rfft/irfft/hfft/ihfft,
2d/n-d variants, fftfreq/rfftfreq, fftshift/ifftshift; norm conventions
"forward"/"backward"/"ortho" at python/paddle/fft.py:61). The reference
dispatches to phi fft kernels (fft_c2c/fft_r2c/fft_c2r); here each transform
is one jax primitive lowered to XLA's FFT HLO, which runs on the TPU's
dedicated FFT path and is differentiable through jax.vjp (FFT is linear, so
the fallback VJP is exact and fuses).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor, apply
from .ops._helpers import defprim, ensure_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = (None, "forward", "backward", "ortho")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be forward, backward or ortho"
        )
    return norm or "backward"


def _seq(v):
    if v is None:
        return None
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),)


# one primitive per transform family; n/s/axis/norm are static (shape-
# determining), so each distinct signature compiles once and is cached.
defprim("fft_c2c", lambda x, *, n, axis, norm: jnp.fft.fft(x, n=n, axis=axis, norm=norm))
defprim("ifft_c2c", lambda x, *, n, axis, norm: jnp.fft.ifft(x, n=n, axis=axis, norm=norm))
defprim("fft_r2c", lambda x, *, n, axis, norm: jnp.fft.rfft(x, n=n, axis=axis, norm=norm))
defprim("fft_c2r", lambda x, *, n, axis, norm: jnp.fft.irfft(x, n=n, axis=axis, norm=norm))
defprim("hfft_p", lambda x, *, n, axis, norm: jnp.fft.hfft(x, n=n, axis=axis, norm=norm))
defprim("ihfft_p", lambda x, *, n, axis, norm: jnp.fft.ihfft(x, n=n, axis=axis, norm=norm))
defprim("fftn_c2c", lambda x, *, s, axes, norm: jnp.fft.fftn(x, s=s, axes=axes, norm=norm))
defprim("ifftn_c2c", lambda x, *, s, axes, norm: jnp.fft.ifftn(x, s=s, axes=axes, norm=norm))
defprim("fftn_r2c", lambda x, *, s, axes, norm: jnp.fft.rfftn(x, s=s, axes=axes, norm=norm))
defprim("fftn_c2r", lambda x, *, s, axes, norm: jnp.fft.irfftn(x, s=s, axes=axes, norm=norm))
# hfftn = fftn over the leading axes, then a Hermitian c2r transform on the
# last axis (verified against scipy.fft.hfftn for all norm conventions).
defprim(
    "hfftn_p",
    lambda x, *, s, axes, norm: jnp.fft.hfft(
        jnp.fft.fftn(x, s=None if s is None else s[:-1], axes=axes[:-1], norm=norm)
        if len(axes) > 1 else x,
        n=None if s is None else s[-1], axis=axes[-1], norm=norm,
    ),
)
defprim(
    "ihfftn_p",
    lambda x, *, s, axes, norm: jnp.fft.ifftn(
        jnp.fft.ihfft(x, n=None if s is None else s[-1], axis=axes[-1], norm=norm),
        s=None if s is None else s[:-1], axes=axes[:-1], norm=norm,
    ) if len(axes) > 1 else jnp.fft.ihfft(
        x, n=None if s is None else s[-1], axis=axes[-1], norm=norm
    ),
)
defprim("fftshift_p", lambda x, *, axes: jnp.fft.fftshift(x, axes=axes))
defprim("ifftshift_p", lambda x, *, axes: jnp.fft.ifftshift(x, axes=axes))


def _1d(prim, x, n, axis, norm):
    x = ensure_tensor(x)
    if n is not None and n <= 0:
        raise ValueError(f"Invalid FFT argument n({n}), it should be positive integer")
    return apply(prim, x, n=None if n is None else int(n), axis=int(axis),
                 norm=_check_norm(norm))


def _nd(prim, x, s, axes, norm):
    x = ensure_tensor(x)
    s, axes = _seq(s), _seq(axes)
    if axes is None:
        axes = tuple(range(x.ndim)) if s is None else tuple(range(x.ndim - len(s), x.ndim))
    if s is not None and len(s) != len(axes):
        raise ValueError("Length of s should match length of axes")
    return apply(prim, x, s=s, axes=axes, norm=_check_norm(norm))


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("fft_c2c", x, n, axis, norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("ifft_c2c", x, n, axis, norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("fft_r2c", x, n, axis, norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("fft_c2r", x, n, axis, norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("hfft_p", x, n, axis, norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _1d("ihfft_p", x, n, axis, norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("fftn_c2c", x, s, axes, norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("ifftn_c2c", x, s, axes, norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("fftn_r2c", x, s, axes, norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("fftn_c2r", x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("hfftn_p", x, s, axes, norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nd("ihfftn_p", x, s, axes, norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("fftn_c2c", x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("ifftn_c2c", x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("fftn_r2c", x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("fftn_c2r", x, s, axes, norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("hfftn_p", x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nd("ihfftn_p", x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    dt = np.dtype(dtype) if dtype is not None else np.dtype("float32")
    return Tensor._from_value(jnp.asarray(np.fft.fftfreq(int(n), float(d)), dtype=dt))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    dt = np.dtype(dtype) if dtype is not None else np.dtype("float32")
    return Tensor._from_value(jnp.asarray(np.fft.rfftfreq(int(n), float(d)), dtype=dt))


def fftshift(x, axes=None, name=None):
    return apply("fftshift_p", ensure_tensor(x), axes=_seq(axes))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift_p", ensure_tensor(x), axes=_seq(axes))
