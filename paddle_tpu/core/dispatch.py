"""Primitive dispatch.

TPU-native re-design of the reference kernel dispatch stack
(reference: paddle/phi/core/kernel_factory.h:58,240,316 KernelKey/Kernel/
KernelFactory; paddle/phi/api/generator/api_base.py:1300-1327 dispatch
template). On TPU the "kernel" is an XLA executable: each primitive is a pure
jax function, jit-compiled once per (static-args, input-avals) signature and
cached — the analog of KernelFactory's per-key kernel map, designed up front
because per-op dispatch is the eager-mode bottleneck on TPU (SURVEY §7).

The same primitive call works on concrete arrays (eager) and on jax tracers
(inside ``paddle_tpu.jit.to_static`` capture), which is how the four execution
modes of the reference collapse into one path.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from . import flags
from .. import observability as _obs

# hot-path gate: two attribute loads when disabled (see observability._gate)
_obs_state = _obs.state

_M_CALLS = _obs.counter(
    "dispatch.calls",
    "primitive dispatches by op and mode (eager | traced | capture)")
_M_CACHE_HITS = _obs.counter(
    "dispatch.cache_hits",
    "_jitted_forward executable-cache hits (op + static-args already seen)")
_M_CACHE_MISSES = _obs.counter(
    "dispatch.cache_misses",
    "_jitted_forward executable-cache misses, by cause")
_M_RETRACES = _obs.counter(
    "dispatch.retraces",
    "jax trace executions of a cached per-op executable, by cause "
    "(new_static_args = first trace after a cache miss; new_avals = "
    "jax.jit re-traced an existing executable for a new input signature)")
_M_VJP_CALLS = _obs.counter(
    "dispatch.vjp_calls", "backward dispatches by op and path "
    "(custom vjp vs jax.vjp rematerialising fallback)")

# (op, static_key) signatures already dispatched — backs the hit/miss
# split without paying lru_cache.cache_info() namedtuple allocation per
# call. Telemetry only: LRU evictions are invisible to it, so growth is
# capped at 2x the executable cache — past the cap, fresh keys keep
# counting as misses (the truthful direction after evictions begin).
_JIT_KEYS_CAP = 16384
_jit_keys_seen: set = set()
_obs.add_reset_hook(_jit_keys_seen.clear)


class Primitive:
    """One op: pure forward fn + optional explicit VJP.

    forward:  fn(*arrays, **static_kwargs) -> array | tuple[array]
    vjp:      fn(grads_out, saved, *, **static_kwargs) -> tuple[array|None]
              where ``saved`` is whatever ``save`` collected at forward time.
    save:     fn(arrays_in, outs) -> pytree of arrays to keep for backward
              (defaults to saving inputs — the TensorWrapper analog,
              reference: fluid/eager/tensor_wrapper.h).
    If ``vjp`` is None, backward falls back to jax.vjp over the forward
    (rematerialised inside one fused XLA program, so the extra FLOPs fuse).
    """

    __slots__ = ("name", "forward", "vjp", "save", "multi_out", "jittable", "nondiff")

    def __init__(
        self,
        name: str,
        forward: Callable,
        vjp: Optional[Callable] = None,
        save: Optional[Callable] = None,
        multi_out: bool = False,
        jittable: bool = True,
        nondiff: bool = False,
    ):
        self.name = name
        self.forward = forward
        self.vjp = vjp
        self.save = save
        self.multi_out = multi_out
        self.jittable = jittable
        self.nondiff = nondiff


# Global registry — the PD_REGISTER_KERNEL analog (kernel_registry.h:196).
PRIMITIVES: Dict[str, Primitive] = {}


def register_primitive(name, forward, **kwargs) -> Primitive:
    p = Primitive(name, forward, **kwargs)
    PRIMITIVES[name] = p
    return p


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


@functools.lru_cache(maxsize=8192)
def _jitted_forward(name: str, static_items):
    """Executable cache keyed by (op, static args); jax.jit adds the
    per-aval level underneath. Analog of KernelFactory::SelectKernelOrThrowError
    + the autotune cache (phi/kernels/autotune/)."""
    prim = PRIMITIVES[name]
    static = dict(static_items)
    if not prim.jittable:
        return lambda *arrays: prim.forward(*arrays, **static)
    n_traces = [0]

    def fn(*arrays):
        # body runs at TRACE time only (jax.jit caches the jaxpr), so this
        # counts retraces: the first trace follows the static-args cache
        # miss, every later one means jax saw a new input-aval signature
        if _obs_state.on:
            n_traces[0] += 1
            _M_RETRACES.inc(op=name, cause="new_static_args"
                            if n_traces[0] == 1 else "new_avals")
        return prim.forward(*arrays, **static)

    return jax.jit(fn)


def _check_nan_inf(name: str, outs):
    level = flags.get_flag("check_nan_inf_level")
    for o in outs:
        if isinstance(o, jax.Array) and jnp.issubdtype(o.dtype, jnp.floating):
            bad = bool(jnp.any(~jnp.isfinite(o)))
            if bad:
                msg = f"NaN/Inf detected in output of op '{name}'"
                if level == 0:
                    raise FloatingPointError(msg)
                import warnings

                warnings.warn(msg)


# --------------------------------------------------------------------------
# Static-graph capture (reference: the PIR program-build path — Python ops
# append pir::Operations instead of executing; SURVEY §3.3). When a
# paddle.static.Program is being built, ops on placeholder values record
# instructions instead of running; shapes propagate via jax.eval_shape.
# --------------------------------------------------------------------------
_capture_program = None


def set_capture_program(prog):
    global _capture_program
    _capture_program = prog


def capture_active() -> bool:
    return _capture_program is not None


def eval_shape(name: str, arrays, static):
    prim = PRIMITIVES[name]
    fn = functools.partial(prim.forward, **static)
    return jax.eval_shape(fn, *arrays)


def call_primitive(name: str, arrays: Sequence[Any], static: Dict[str, Any]):
    """Run a primitive's forward. Returns tuple of raw outputs.

    NaN/Inf watchdog (reference: fluid/eager/nan_inf_utils.cc behind
    FLAGS_check_nan_inf) only fires on concrete values, never on tracers.
    """
    if _capture_program is not None and any(
        isinstance(a, jax.ShapeDtypeStruct) for a in arrays
    ):
        if _obs_state.on:
            _M_CALLS.inc(op=name, mode="capture")
        outs = _capture_program.record(name, arrays, static)
        return outs if isinstance(outs, tuple) else (outs,)
    prim = PRIMITIVES[name]
    on = _obs_state.on
    if on:
        _M_CALLS.inc(op=name, mode="traced" if any(
            isinstance(a, jax.core.Tracer) for a in arrays) else "eager")
    if flags.get_flag("eager_op_jit") and prim.jittable:
        static_key = _hashable(static)
        if on:
            sig = (name, static_key)
            if sig in _jit_keys_seen:
                _M_CACHE_HITS.inc(op=name)
            else:
                if len(_jit_keys_seen) < _JIT_KEYS_CAP:
                    _jit_keys_seen.add(sig)
                _M_CACHE_MISSES.inc(op=name, cause="new_static_args")
        fn = _jitted_forward(name, static_key)
        outs = fn(*arrays)
    else:
        outs = prim.forward(*arrays, **static)
    outs = outs if isinstance(outs, tuple) else (outs,)
    if flags.get_flag("check_nan_inf") and not any(
        isinstance(a, jax.core.Tracer) for a in outs
    ):
        _check_nan_inf(name, outs)
    return outs


@functools.lru_cache(maxsize=8192)
def _jitted_vjp_fallback(name: str, static_items):
    """Generic backward: rematerialise forward inside the grad program.
    XLA CSE/fusion absorbs the recompute; this is the default path for ops
    without a hand-written VJP."""
    prim = PRIMITIVES[name]
    static = dict(static_items)

    def bwd(grads_out, *arrays):
        f = lambda *a: prim.forward(*a, **static)
        outs, vjp_fn = jax.vjp(f, *arrays)
        if not isinstance(outs, tuple):
            grads_out = grads_out[0]
        return vjp_fn(grads_out)

    return jax.jit(bwd) if prim.jittable else bwd


def call_vjp(name: str, grads_out, saved, static: Dict[str, Any]):
    """Run a primitive's backward. grads_out: tuple aligned with outputs
    (zeros filled in by the engine for unused outputs)."""
    prim = PRIMITIVES[name]
    if _obs_state.on:
        _M_VJP_CALLS.inc(op=name,
                         path="custom" if prim.vjp is not None
                         else "fallback")
    if prim.vjp is not None:
        grads = prim.vjp(grads_out, saved, **static)
    else:
        # fallback saved = the input arrays tuple
        fn = _jitted_vjp_fallback(name, _hashable(static))
        grads = fn(tuple(grads_out), *saved)
    return tuple(grads) if isinstance(grads, (tuple, list)) else (grads,)


def dispatch_cache_info():
    return {
        "forward": _jitted_forward.cache_info(),
        "vjp_fallback": _jitted_vjp_fallback.cache_info(),
    }


def positional_capacity(fn) -> tuple:
    """(min_required_positional, max_positional_or_None_if_variadic) of a
    callable, or (None, None) when the signature is opaque (C builtins).
    Shared by primitive_metadata and tools/lint_registry.py so the
    analysis layer and the registry lint agree on what a signature can
    accept."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None, None
    pos = [p for p in sig.parameters.values()
           if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    if any(p.kind == p.VAR_POSITIONAL for p in sig.parameters.values()):
        return sum(1 for p in pos if p.default is p.empty), None
    return sum(1 for p in pos if p.default is p.empty), len(pos)


def primitive_metadata(name: str) -> Dict[str, Any]:
    """Introspected per-primitive metadata for the analysis/lint layer
    (static/analysis, tools/lint_registry.py) — the KernelFactory
    attribute surface (kernel_factory.h KernelKey/KernelArgsDef) reduced
    to what a flat jax registry can answer: flags, grad wiring, and the
    positional/keyword capacity of forward/vjp/save."""
    import inspect

    prim = PRIMITIVES[name]
    meta: Dict[str, Any] = {
        "name": prim.name,
        "jittable": prim.jittable,
        "multi_out": prim.multi_out,
        "nondiff": prim.nondiff,
        "has_vjp": prim.vjp is not None,
        "has_save": prim.save is not None,
        "backward_only": prim.forward is None,
        "min_arity": None,
        "max_arity": None,
        "static_kwargs": (),
        "vjp_capacity": None,
        "save_capacity": None,
    }
    if callable(prim.vjp):
        meta["vjp_capacity"] = positional_capacity(prim.vjp)
    if callable(prim.save):
        meta["save_capacity"] = positional_capacity(prim.save)
    if prim.forward is None:
        return meta
    meta["min_arity"], meta["max_arity"] = positional_capacity(prim.forward)
    try:
        sig = inspect.signature(prim.forward)
    except (TypeError, ValueError):
        return meta
    meta["static_kwargs"] = tuple(
        p.name for p in sig.parameters.values() if p.kind == p.KEYWORD_ONLY)
    return meta
