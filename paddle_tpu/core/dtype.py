"""Dtype system.

TPU-native re-design of the reference dtype surface
(reference: paddle/phi/common/data_type.h, python/paddle/framework/dtype.py).
Dtypes are thin aliases of numpy/jax dtypes; bfloat16 is the TPU-preferred
half precision (MXU-native), float64 is supported but discouraged on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (jnp dtypes so they flow straight into XLA).
bool = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_STR_TO_DTYPE = {
    "bool": bool,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # paddle-compat spellings
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
INTEGER = {uint8, int8, int16, int32, int64}
COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize any dtype spec (str / np.dtype / jnp dtype / paddle_tpu dtype)
    to a canonical numpy dtype object usable by jax."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return np.dtype(_STR_TO_DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype ('float32', 'bfloat16', ...)."""
    return np.dtype(dtype).name


_EXT_FLOATS = tuple(
    np.dtype(d) for d in (bfloat16, float8_e4m3fn, float8_e5m2)
)


def is_floating_point(dtype):
    d = np.dtype(dtype)
    return d.kind == "f" or d in _EXT_FLOATS


def is_integer(dtype):
    return np.dtype(dtype).kind in ("i", "u")


def is_complex(dtype):
    return np.dtype(dtype).kind == "c"


# paddle's implicit-promotion table is numpy-style; jax follows the same
# lattice under jax.numpy with x64 enabled/disabled. We rely on jnp.promote_types.
promote_types = jnp.promote_types


def default_float_dtype():
    from . import flags

    return convert_dtype(flags.get_flag("default_dtype"))
