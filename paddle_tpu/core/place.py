"""Device places.

TPU-native re-design of the reference Place hierarchy
(reference: paddle/phi/common/place.h — CPUPlace/GPUPlace/XPUPlace/CustomPlace).
A Place names a jax.Device; TPUPlace is the first-class accelerator.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place: names a logical device."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    # -- jax bridge --------------------------------------------------------
    def get_device(self):
        """Resolve to a jax.Device (raises if the backend is unavailable)."""
        devs = _devices_for(self.device_type)
        if self.device_id >= len(devs):
            raise RuntimeError(
                f"{self!r}: only {len(devs)} {self.device_type} device(s) visible"
            )
        return devs[self.device_id]

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    """The accelerator place. Reference GPUPlace analog (place.h)."""

    device_type = "tpu"


# Compat alias: code written against the reference uses CUDAPlace for "the
# accelerator"; on this framework that is the TPU.
CUDAPlace = TPUPlace


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


@functools.lru_cache(maxsize=None)
def _devices_for(device_type: str):
    if device_type == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple(jax.devices())
    # tpu: accept any accelerator backend (tpu, or tunneled platforms that
    # expose TPU chips under an experimental platform name).
    try:
        return tuple(jax.devices("tpu"))
    except RuntimeError:
        pass
    devs = tuple(d for d in jax.devices() if d.platform != "cpu")
    if devs:
        return devs
    return tuple(jax.devices())


@functools.lru_cache(maxsize=None)
def default_place() -> Place:
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return TPUPlace(0)
    return CPUPlace(0)


_expected_place = None


def get_device() -> str:
    """paddle.device.get_device() parity: 'tpu:0' or 'cpu'."""
    p = _expected_place or default_place()
    return "cpu" if isinstance(p, CPUPlace) else f"{p.device_type}:{p.device_id}"


def set_device(device: str) -> Place:
    """paddle.device.set_device parity ('tpu', 'tpu:0', 'cpu', 'gpu'→tpu)."""
    global _expected_place
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name == "cpu":
        _expected_place = CPUPlace(idx)
    elif name in ("tpu", "gpu", "xpu", "npu"):
        _expected_place = TPUPlace(idx)
    else:
        _expected_place = CustomPlace(name, idx)
    return _expected_place


def expected_place() -> Place:
    return _expected_place or default_place()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(_devices_for("tpu"))


class CUDAPinnedPlace(Place):
    """Reference: paddle.CUDAPinnedPlace — page-locked host staging memory.
    On TPU, host staging buffers are managed by PJRT; this place maps to
    host memory."""

    device_type = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(gpu_pinned)"
