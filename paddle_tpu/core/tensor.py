"""Eager Tensor.

TPU-native re-design of the reference eager Tensor
(reference: paddle/fluid/pybind/eager.cc Tensor type, eager_method.cc tensor
methods, phi/core/dense_tensor.h storage). Here a Tensor wraps a jax.Array
(PJRT buffer on TPU) or a jax Tracer (under ``jit.to_static`` capture) plus
autograd metadata (AutogradMeta analog: stop_gradient, grad, producing node).

Operator methods (``__add__``, ``matmul``...) are monkey-patched on by the
ops layer, mirroring python/paddle/base/dygraph/math_op_patch.py.
"""
from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dispatch, dtype as dtypes, place as places
from ..autograd import engine


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_node",
        "_out_slot",
        "_accum",
        "_grad_value",
        "_grad_hooks",
        "_retain_grads",
        "name",
        "persistable",
        "_dist_attr",
        "__weakref__",
    )

    _name_counter = 0

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        self._value = value
        self.stop_gradient = stop_gradient
        self._node = None
        self._out_slot = 0
        self._accum = None
        self._grad_value = None
        self._grad_hooks: List = []
        self._retain_grads = False
        if name is None:
            Tensor._name_counter += 1
            name = f"generated_tensor_{Tensor._name_counter}"
        self.name = name
        self.persistable = False
        self._dist_attr = None  # (ProcessMesh, placements) when distributed

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @classmethod
    def _from_value(cls, value, stop_gradient: bool = True) -> "Tensor":
        return cls(value, stop_gradient=stop_gradient)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        v = self._value
        if isinstance(v, jax.core.Tracer):
            return places.expected_place()
        dev = next(iter(v.devices())) if hasattr(v, "devices") else None
        if dev is None or dev.platform == "cpu":
            return places.CPUPlace(0)
        return places.TPUPlace(dev.id)

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def _accum_node(self):
        if self._accum is None:
            self._accum = engine.AccumulationNode(self)
        return self._accum

    # ------------------------------------------------------------------
    # grad surface
    # ------------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_value is None:
            return None
        return Tensor._from_value(self._grad_value)

    @grad.setter
    def grad(self, g):
        if g is None:
            self._grad_value = None
        else:
            self._grad_value = g._value if isinstance(g, Tensor) else jnp.asarray(g)

    def clear_grad(self):
        self._grad_value = None

    clear_gradient = clear_grad

    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        """loss.backward() (reference: tensor_patch_methods.py:86 →
        eager_functions.cc:145 run_backward → eager/backward.cc:105)."""
        engine.run_backward(
            [self],
            [grad_tensor] if grad_tensor is not None else None,
            retain_graph=retain_graph,
        )

    def register_hook(self, hook):
        """Grad hook (reference: grad_node_info.h hooks). Returns a removable
        handle."""
        if self._node is not None:
            hooks = self._node.out_hooks.setdefault(self._out_slot, [])
            hooks.append(hook)
            return _HookHandle(hooks, hook)
        self._grad_hooks.append(hook)
        return _HookHandle(self._grad_hooks, hook)

    def retain_grads(self):
        if self._node is not None and not self._retain_grads:
            self._retain_grads = True
            acc = self._accum_node()
            node, slot = self._node, self._out_slot

            def _store(g):
                acc.accumulate(g._value)
                return None

            self._node.out_hooks.setdefault(slot, []).append(_store)

    # ------------------------------------------------------------------
    # value access
    # ------------------------------------------------------------------
    def numpy(self) -> np.ndarray:
        v = self._value
        if isinstance(v, jax.core.Tracer):
            raise RuntimeError(
                "Tensor.numpy() is not available inside jit.to_static capture"
            )
        return np.asarray(v)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def detach(self) -> "Tensor":
        t = Tensor._from_value(self._value, stop_gradient=True)
        t.name = self.name + ".detach"
        t._dist_attr = self._dist_attr
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import assign

        return assign(self)

    def cpu(self) -> "Tensor":
        return Tensor._from_value(jax.device_put(self._value, jax.devices("cpu")[0]),
                                  stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        # to(dtype), to(place), to(device_str)
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str,)) and a in ("cpu", "tpu", "gpu"):
                dev = jax.devices("cpu")[0] if a == "cpu" else places.TPUPlace(0).get_device()
                out = Tensor._from_value(jax.device_put(out._value, dev), out.stop_gradient)
            elif isinstance(a, places.Place):
                out = Tensor._from_value(jax.device_put(out._value, a.get_device()), out.stop_gradient)
            else:
                out = out.astype(a)
        return out

    def astype(self, dt) -> "Tensor":
        from ..ops import cast

        return cast(self, dt)

    cast = astype

    # value mutation (in-place assign; autograd-invisible like reference
    # Tensor.set_value, tensor_patch_methods.py set_value)
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        arr = jnp.asarray(value, dtype=self.dtype)
        if tuple(arr.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._value.shape}"
            )
        self._value = arr

    def _replace_value(self, value):
        """In-place op support: rebind storage, drop stale graph link."""
        self._value = value

    def copy_(self, other, blocking: bool = True):
        self.set_value(other)
        return self

    # ------------------------------------------------------------------
    # python protocol
    # ------------------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        v = self._value
        if isinstance(v, jax.core.Tracer):
            return f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, traced)"
        sg = self.stop_gradient
        return (
            f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
            f"place={self.place}, stop_gradient={sg},\n{np.asarray(v)})"
        )

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy().reshape(-1)[0]) if self.size == 1 else int(self.numpy())

    def __float__(self):
        return float(self.numpy().reshape(-1)[0]) if self.size == 1 else float(self.numpy())

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.numpy().item(), spec)
        return format(str(self), spec)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # __getitem__/__setitem__/arithmetic are patched in by paddle_tpu.ops


class _HookHandle:
    def __init__(self, hooks, hook):
        self._hooks = hooks
        self._hook = hook

    def remove(self):
        try:
            self._hooks.remove(self._hook)
        except ValueError:
            pass


# A Parameter is a Tensor with stop_gradient=False + trainable flag
# (reference: python/paddle/base/framework.py EagerParamBase).
class Parameter(Tensor):
    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed")

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False

    @property
    def trainable_(self):
        return self.trainable


# amp cast hook, installed by paddle_tpu.amp at import (avoids circular dep)
_amp_hook = None


def _install_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


# --------------------------------------------------------------------------
# The op-application path: every op in paddle_tpu.ops funnels through here.
# Analog of the generated *_ad_func bodies (eager_gen.py:321): run kernel,
# save tensors, create GradNode, wire edges.
# --------------------------------------------------------------------------
def apply(prim_name: str, *tensors: Tensor, **static) -> Any:
    # All positional args must be Tensors (ops convert scalars/None upstream)
    # so VJP results align 1:1 with recorded edges.
    prim = dispatch.PRIMITIVES[prim_name]
    arrays = tuple(t._value for t in tensors)
    if _amp_hook is not None:
        arrays = _amp_hook(prim_name, arrays)
    outs = dispatch.call_primitive(prim_name, arrays, static)
    requires = (not prim.nondiff) and engine.grad_enabled() and any(
        not t.stop_gradient for t in tensors
    ) and not dispatch.capture_active()
    node = None
    if requires:
        saved = prim.save(arrays, outs) if prim.save else arrays
        # input Tensor refs kept for create_graph replay (TensorWrapper
        # analog): the replay differentiates jax.vjp over the forward with
        # the ORIGINAL inputs, so custom save/vjp fast paths don't sever
        # the second-order graph
        node = engine.record_op(
            prim_name, static, saved, tensors, outs, saved_tensors=tensors
        )
    result = []
    for i, o in enumerate(outs):
        t = Tensor._from_value(o, stop_gradient=not requires)
        if node is not None:
            t._node = node
            t._out_slot = i
        result.append(t)
    if prim.multi_out:
        return tuple(result)
    return result[0]
