"""Runtime flags registry.

TPU-native equivalent of the reference flag registry
(reference: paddle/common/flags.cc — 177 PHI_DEFINE_EXPORTED_* flags,
python/paddle/base/framework.py set_flags/get_flags).

Flags are process-global, overridable via environment variables named
``FLAGS_<name>`` (checked at first read), and via ``set_flags``.

When the native runtime (csrc/ptpu_flags.cc) is available, the C++
registry is the source of truth — values written from either side are
visible to both, mirroring how the reference shares one gflags registry
between C++ and Python (core.globals()).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

_LOCK = threading.RLock()


def _native():
    """The native module if its library is ALREADY loaded, else None.

    Deliberately never triggers a build: flags are touched on `import
    paddle_tpu`, and the first import must not block on a g++ link. When
    some other component loads the library, _on_native_loaded() below syncs
    this registry into the native one and subsequent calls delegate.
    """
    global _NATIVE_MOD
    if _NATIVE_MOD is None:
        try:
            from paddle_tpu import native

            _NATIVE_MOD = native
        except Exception:
            return None
    return _NATIVE_MOD if _NATIVE_MOD.loaded() else None


_NATIVE_MOD = None


def _flag_str(value) -> str:
    return str(int(value)) if isinstance(value, bool) else str(value)


def _on_native_loaded(lib=None):
    """Called by paddle_tpu.native right after the C++ library loads:
    mirror every Python-registered flag (and any explicit overrides) into
    the native registry so C++ and Python share one flag state."""
    from paddle_tpu import native

    with _LOCK:
        for name, f in _REGISTRY.items():
            native.flag_define(name, _flag_str(f.default), f.doc)
            if f.env_checked:
                # Python already resolved env/explicit sets; push the result.
                native.flag_set(name, _flag_str(f.value))


class _Flag:
    __slots__ = ("name", "default", "value", "doc", "type", "env_checked")

    def __init__(self, name, default, doc, type_):
        self.name = name
        self.default = default
        self.value = default
        self.doc = doc
        self.type = type_
        self.env_checked = False


_REGISTRY: Dict[str, _Flag] = {}


def _coerce(type_, raw: str):
    if type_ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return type_(raw)


def define_flag(name: str, default: Any, doc: str = "", type_=None):
    """Register a flag (analog of PHI_DEFINE_EXPORTED_* at common/flags.cc:31)."""
    with _LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
        f = _Flag(name, default, doc, type_ or type(default))
        _REGISTRY[name] = f
        nat = _native()
        if nat is not None:
            sd = str(int(default)) if isinstance(default, bool) else str(default)
            nat.flag_define(name, sd, doc)
        return f


def get_flag(name: str):
    with _LOCK:
        f = _REGISTRY[name]
        nat = _native()
        if nat is not None:
            raw = nat.flag_get(name)
            if raw is not None:
                return _coerce(f.type, raw)
        if not f.env_checked:
            f.env_checked = True
            raw = os.environ.get("FLAGS_" + name)
            if raw is not None:
                f.value = _coerce(f.type, raw)
        return f.value


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags equivalent."""
    with _LOCK:
        for k, v in flags.items():
            k = k.removeprefix("FLAGS_")
            if k not in _REGISTRY:
                raise KeyError(f"Unknown flag: {k}")
            f = _REGISTRY[k]
            f.env_checked = True
            f.value = _coerce(f.type, v) if isinstance(v, str) else f.type(v)
            nat = _native()
            if nat is not None:
                sv = str(int(f.value)) if isinstance(f.value, bool) \
                    else str(f.value)
                nat.flag_set(k, sv)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {"FLAGS_" + n: get_flag(n) for n in names}


def all_flags():
    with _LOCK:
        return {n: get_flag(n) for n in list(_REGISTRY)}


# ---------------------------------------------------------------------------
# Core flag set (subset of the reference's 177, the ones with TPU meaning).
# ---------------------------------------------------------------------------
define_flag("default_dtype", "float32", "default floating dtype for tensor creation")
define_flag("check_nan_inf", False, "NaN/Inf watchdog on op outputs (flags.cc:72)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: warn only (flags.cc:86)")
define_flag("eager_op_jit", True, "jit-compile per-op eager executions with caching")
define_flag("deterministic", False, "force deterministic kernels (cudnn_deterministic analog)")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; XLA/PJRT owns HBM")
define_flag("use_stride_kernel", True, "views share storage where jax allows aliasing")
define_flag("embedding_deterministic", 0, "deterministic embedding grad scatter")
define_flag("flash_attn_version", 2, "flash-attention kernel generation")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|float32|tensorfloat32")
define_flag("log_level", 0, "VLOG analog verbosity")
define_flag("benchmark", False, "sync after each op for timing")
define_flag("stop_check_timeout", 900, "collective watchdog timeout seconds (parallel.py:1133)")
define_flag("cache_inference_while_scope", False, "parity placeholder")
define_flag("check_embedding_bounds", True,
            "eager-mode embedding id range check (one blocking "
            "device->host sync per call; disable in eager inner loops "
            "where throughput matters — jit paths never pay it)")
define_flag("observability", False,
            "record runtime metrics/events at the instrumented hot paths "
            "(dispatch, Executor, PassManager, jit) — see "
            "paddle_tpu.observability; also enabled by "
            "PADDLE_TPU_METRICS_DUMP=<path>")
define_flag("observability_max_events", 4096,
            "ring-buffer capacity of the observability structured-event "
            "log (oldest events drop first)")
define_flag("observability_flight_events", 512,
            "ring-buffer capacity of the flight recorder (last-N runtime "
            "events serialized to PADDLE_TPU_FLIGHT_DIR on crash/timeout)")
define_flag("optimize_programs", False,
            "run the lint->rewrite optimization pipeline "
            "(static.analysis.optimize_program: CSE, cast/transpose-chain "
            "collapse, dead-op and unused-feed pruning) on a cached clone "
            "of every Program before Executor.run compiles it; also "
            "enabled by PADDLE_TPU_OPTIMIZE=1")
define_flag("use_pallas_flash_attention", True,
            "use the Pallas flash-attention kernel on TPU backends")
define_flag("use_pallas_rms_norm", True,
            "use the Pallas fused RMSNorm kernel when shapes are lane-aligned")
define_flag("pallas_force_interpret", False,
            "run Pallas kernels in interpret mode on non-TPU backends "
            "(testing only — the interpreter is orders slower than XLA)")
define_flag("observability_ts_points", 512,
            "ring-buffer capacity per metric time-series (points kept by "
            "observability/timeseries.SeriesRecorder; oldest samples drop "
            "first — bounds health-monitoring memory no matter how long "
            "the job runs)")
