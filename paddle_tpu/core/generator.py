"""RNG seed management.

TPU-native re-design of the reference generator
(reference: paddle/phi/core/generator.h — Philox counter per device;
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py —
RNGStatesTracker keeping TP replicas coherent for dropout).

jax uses counter-based threefry keys; the eager layer keeps one root key per
"state name" and splits a fresh subkey per draw. Under ``jit.to_static``
tracing the same API yields traced keys, so compiled steps stay functional.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax

_DEFAULT = "global_seed"


class Generator:
    """One named RNG stream (generator.h analog: seed + offset counter)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._offset = 0
        self._key = jax.random.key(self._seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._offset = 0
        self._key = jax.random.key(self._seed)
        return self

    def initial_seed(self) -> int:
        return self._seed

    @property
    def seed(self) -> int:
        return self._seed

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state):
        self.manual_seed(state["seed"])
        # replay the counter so resume is bit-exact
        for _ in range(state["offset"]):
            self._key, _ = jax.random.split(self._key)
        self._offset = state["offset"]

    def next_key(self):
        """Split off a fresh subkey (the Philox-offset bump analog)."""
        self._key, sub = jax.random.split(self._key)
        self._offset += 1
        return sub


class RNGStatesTracker:
    """Named RNG streams so TP/PP replicas can agree or differ on demand
    (reference: fleet/meta_parallel/parallel_layers/random.py RNGStatesTracker).

    - 'global_seed'       : identical across model-parallel ranks
    - 'local_seed'        : differs per rank (dropout inside TP regions)
    """

    def __init__(self):
        self._states: Dict[str, Generator] = {}
        self._lock = threading.RLock()

    def add(self, name: str, seed: int):
        with self._lock:
            self._states[name] = Generator(seed)

    def get(self, name: str = _DEFAULT) -> Generator:
        with self._lock:
            if name not in self._states:
                self._states[name] = Generator(0)
            return self._states[name]

    def get_states(self):
        with self._lock:
            return {k: g.get_state() for k, g in self._states.items()}

    def set_states(self, states):
        with self._lock:
            for k, s in states.items():
                self._states.setdefault(k, Generator()).set_state(s)


_tracker = RNGStatesTracker()

# --------------------------------------------------------------------------
# Trace-mode key source: inside ``jit.to_static`` capture, random draws must
# come from a traced key input (not the concrete eager key, which would be
# baked into the compiled program as a constant). The jit layer pushes the
# per-call key here; next_key() then derives subkeys by fold_in/split.
# --------------------------------------------------------------------------
_trace = threading.local()


class trace_key_scope:
    def __init__(self, key):
        self._key = key

    def __enter__(self):
        stack = getattr(_trace, "stack", None)
        if stack is None:
            stack = _trace.stack = []
        stack.append([self._key, 0])
        return self

    def __exit__(self, *exc):
        _trace.stack.pop()
        return False


def _trace_next_key():
    stack = getattr(_trace, "stack", None)
    if not stack:
        return None
    entry = stack[-1]
    entry[0], sub = jax.random.split(entry[0])
    entry[1] += 1
    return sub


def _snapshot_keys():
    """Capture the current key source (for recompute replay): the top traced
    key under jit capture, else the eager local stream's key."""
    stack = getattr(_trace, "stack", None)
    if stack:
        return stack[-1][0]
    return _tracker.get("local_seed")._key


class _restore_keys_scope(trace_key_scope):
    """Replay draws from a snapshotted key (recompute backward). Reuses the
    trace-key stack so it works identically eager and under capture."""

    def __init__(self, snapshot_key):
        super().__init__(snapshot_key)


def default_generator() -> Generator:
    return _tracker.get(_DEFAULT)


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def seed(value: int):
    """paddle.seed parity: reseed the default stream (and local stream base)."""
    _tracker.get(_DEFAULT).manual_seed(value)
    _tracker.get("local_seed").manual_seed(value + 1)
    return default_generator()


def next_key(name: str = _DEFAULT):
    traced = _trace_next_key()
    if traced is not None:
        return traced
    return _tracker.get(name).next_key()
