"""paddle_tpu.serve — continuous-batching LLM serving engine.

The serving layer ROADMAP item 1 asks for: a request scheduler that
admits, continuously batches, preempts and retires concurrent decode
streams over the paged KV block pool, driven by one persistent compiled
decode step and the decode-specialized paged-attention kernel
(``ops/pallas/paged_attention.py``). See ``engine.py`` for the
admission/eviction contract, ``load.py`` for the Poisson load
generator behind ``tools/serve_load.py``, and the README "Serving"
section for a worked example.
"""
from .engine import Request, ServeEngine
from .load import LoadResult, run_load
from .pool import BlockPool, PoolExhaustedError
from .prefix import PrefixCache

__all__ = ["ServeEngine", "Request", "BlockPool", "PoolExhaustedError",
           "PrefixCache", "run_load", "LoadResult"]
