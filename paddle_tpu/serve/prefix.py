"""Prefix cache: a radix tree over full KV blocks for cross-request
KV reuse (the PagedAttention/vLLM automatic-prefix-caching design).

Block-table indirection already lets any table row point at any
physical block; this index makes that sharable. Every FULL block a
stream writes is registered under the chain of block-sized token
chunks that produced it — node identity is the exact token tuple, not
a lossy hash, so a match can never alias two different prefixes to the
same KV. At admission the engine walks the tree with the new prompt's
chunks (:meth:`match`) and mounts the longest matched chain of
physical blocks directly into the request's block table: the stream
decodes from the SAME blocks every earlier stream with that prefix
wrote, and prefill runs only on the unshared suffix.

Lifecycle discipline (enforced with ``BlockPool``'s refcounts):

- a matched block is ``acquire``-d per sharing stream; finish and
  preemption ``release`` it;
- a registered block whose refcount drops to 0 is RETAINED in the
  pool's cached state and parked here on an LRU (:meth:`note_cached`)
  — its KV stays resident so a future request can still match it;
- when the pool runs dry the engine calls :meth:`evict`, which
  reclaims LRU-oldest cached blocks (never a referenced one — the
  pool hard-errors on that) and unregisters their subtrees: a chain
  with a missing parent is unmatchable, so orphaned descendants are
  dropped (and reclaimed too when they are themselves cached).
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

from .pool import BlockPool

__all__ = ["PrefixCache"]


class _Node:
    """One full block of KV: ``key`` is the exact token chunk that
    filled it, reached through ``parent`` — the path from the root
    spells the whole token prefix this block's KV depends on."""

    __slots__ = ("key", "block", "parent", "children")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}


class PrefixCache:
    """Trie of full-block token chunks -> resident physical block ids."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._root = _Node((), -1, None)
        self._by_block: Dict[int, _Node] = {}
        # refcount-0 registered blocks, oldest-touched first (eviction
        # order); referenced blocks are NOT here — they are unevictable
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()

    # -- introspection -----------------------------------------------------
    @property
    def registered_blocks(self) -> int:
        return len(self._by_block)

    @property
    def evictable_blocks(self) -> int:
        return len(self._lru)

    def is_registered(self, block: int) -> bool:
        return int(block) in self._by_block

    # -- matching ----------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest-prefix match: walk the tree with ``tokens`` in
        block-sized chunks and return the matched chain of physical
        block ids (possibly empty). Only FULL chunks participate — a
        partial tail block is never sharable. Touches every matched
        block's LRU recency."""
        bs = self.block_size
        node = self._root
        out: List[int] = []
        for i in range(len(tokens) // bs):
            child = node.children.get(
                tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            out.append(child.block)
            if child.block in self._lru:
                self._lru.move_to_end(child.block)
            node = child
        return out

    def node_for(self, tokens: Sequence[int]) -> "_Node":
        """The trie node at the end of ``tokens``'s matched chain (the
        root when nothing matches) — the registration cursor a stream
        carries so each later full block registers in O(block_size)."""
        bs = self.block_size
        node = self._root
        for i in range(len(tokens) // bs):
            child = node.children.get(
                tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            node = child
        return node

    # -- registration ------------------------------------------------------
    def register(self, parent: "_Node", chunk: Sequence[int],
                 block: int) -> "_Node":
        """Register ``block`` as holding the KV of ``chunk`` (exactly
        ``block_size`` tokens) extending ``parent``'s prefix. If the
        chunk is already registered (two streams raced the same
        prefix), the existing node wins — the caller's block simply
        stays private and unshared. Returns the node to carry forward
        as the stream's registration cursor."""
        key = tuple(int(t) for t in chunk)
        if len(key) != self.block_size:
            raise ValueError(
                f"register(): chunk has {len(key)} tokens, expected a "
                f"full block of {self.block_size} — partial blocks are "
                f"not sharable")
        existing = parent.children.get(key)
        if existing is not None:
            return existing
        node = _Node(key, int(block), parent)
        parent.children[key] = node
        self._by_block[int(block)] = node
        return node

    # -- refcount-edge notifications --------------------------------------
    def note_cached(self, blocks: Sequence[int]) -> None:
        """Registered blocks just dropped to refcount 0 (pool parked
        them in the cached state) — enqueue for LRU eviction."""
        for b in blocks:
            b = int(b)
            if b in self._by_block:
                self._lru[b] = None
                self._lru.move_to_end(b)

    def note_acquired(self, blocks: Sequence[int]) -> None:
        """Blocks just gained a live reference — no longer evictable."""
        for b in blocks:
            self._lru.pop(int(b), None)

    # -- eviction ----------------------------------------------------------
    def evict(self, pool: BlockPool, n: int) -> int:
        """Reclaim up to ``n`` cached blocks back to the pool's free
        list, LRU-oldest first; returns how many were actually
        reclaimed. Referenced blocks are untouchable by construction
        (they are never on the LRU)."""
        reclaimed = 0
        while reclaimed < n and self._lru:
            block, _ = self._lru.popitem(last=False)
            reclaimed += self._drop_subtree(self._by_block[block], pool)
        return reclaimed

    def reset(self, pool: BlockPool) -> int:
        """Drop every evictable entry (compile-warm pollution, test
        isolation). Returns the number of blocks reclaimed. Referenced
        registrations survive — their streams are still live."""
        n = 0
        while self._lru:
            block, _ = self._lru.popitem(last=False)
            n += self._drop_subtree(self._by_block[block], pool)
        return n

    def _drop_subtree(self, node: "_Node", pool: BlockPool) -> int:
        """Unregister ``node`` and every descendant (a chain with a
        missing parent can never be matched again); reclaim the cached
        ones. A cached node never has referenced descendants — a
        stream holding a child block holds the whole prefix chain —
        so everything under it is cached or already unregistered."""
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        reclaimed = 0
        stack = [node]
        while stack:
            cur = stack.pop()
            stack.extend(cur.children.values())
            cur.children = {}
            self._by_block.pop(cur.block, None)
            self._lru.pop(cur.block, None)
            if pool.is_cached(cur.block):
                pool.reclaim([cur.block])
                reclaimed += 1
        return reclaimed
