"""Synthetic heavy-traffic load generator for the serving engine.

Poisson arrivals (exponential inter-arrival gaps at ``rate`` req/s) of
requests with mixed prompt/output lengths, submitted against a live
:class:`~paddle_tpu.serve.engine.ServeEngine` in wall-clock time while
the engine loop keeps stepping — so queueing, continuous batching and
preemption all happen under realistic contention, and TTFT includes
real queue wait.

``tools/serve_load.py`` is the CLI; ``bench.py --config serve`` runs
the same generator for the BENCH record (p50/p99 TTFT + aggregate
tokens/sec land in the ``--metrics`` roll-up via the ``serve.``
registry series this run populates).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List

import numpy as np

from . import engine as _engine_mod
from .engine import ServeEngine

__all__ = ["run_load", "LoadResult", "default_serving_setup",
           "warm_engine"]


class _WallClock:
    """The default ``run_load`` clock: real wall time. Tests swap in
    ``observability.FakeClock`` (same ``time()``/``sleep()`` surface)
    so Poisson timing assertions stop depending on host scheduling."""

    sleep = staticmethod(time.sleep)
    time = staticmethod(time.perf_counter)


def default_serving_setup(on_tpu: bool):
    """ONE source for the model config + engine/load defaults shared by
    ``bench.py --config serve`` and ``tools/serve_load.py`` — tuning
    the serving shape here keeps the BENCH record and the CLI it
    claims parity with in sync."""
    from ..models import LlamaConfig

    if on_tpu:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=10, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048)
        params = dict(rate=30.0, requests=48, slots=8, num_blocks=96,
                      block_size=128, max_seq_len=1024,
                      prompt_len=(32, 128), max_new=(16, 64))
    else:
        config = LlamaConfig.tiny()
        params = dict(rate=300.0, requests=16, slots=3, num_blocks=24,
                      block_size=8, max_seq_len=48,
                      prompt_len=(4, 12), max_new=(4, 8))
    return config, params


def warm_engine(engine: ServeEngine, max_prompt_len=None):
    """Compile the decode step and EVERY reachable prefill bucket
    outside the measured window. Prefill compiles once per pow2 length
    bucket; a bucket first hit mid-load would bill a full XLA compile
    to that request's TTFT — turning the p99 BENCH record into a
    compiler benchmark (a ~50x p99/p50 ratio was the symptom)."""
    vocab = int(engine._arrays["embed"].shape[0])
    # the longest ADMISSIBLE prompt: max_new >= 1 bounds it at
    # max_seq_len - 1, and its n-token working set must fit the pool.
    # Warming at every pow2 below that cap plus the cap itself covers
    # every value the (monotone) bucket function can take — including
    # the max_seq_len-capped TOP bucket, which a pow2-only sweep
    # misses whenever the cap lands on a power of two.
    cap = min(engine.max_seq_len - 1,
              engine.pool.num_blocks * engine.block_size)
    if max_prompt_len is not None:
        cap = min(cap, int(max_prompt_len))
    lens, b = [], 8
    while b < cap:
        lens.append(b)              # a len-b prompt fills bucket b exactly
        b *= 2
    lens.append(cap)                # the final (possibly capped) bucket
    for n in dict.fromkeys(lens):
        if n < 1:
            continue
        req = engine.submit(np.arange(n) % (vocab - 1) + 1,
                            max_new_tokens=1, warmup=True)
        engine.run()
        if req.state != "FINISHED":   # pragma: no cover — engine contract
            raise RuntimeError("warm-up request did not finish")
    if engine._prefix is not None:
        # suffix-prefill buckets: a prompt that shares its first block
        # with a resident one prefills only the suffix, which buckets
        # through the SAME pow2 function — warm each reachable bucket
        # by re-using one warm block and varying the suffix length
        bs = engine.block_size
        base = np.arange(bs) % (vocab - 1) + 1
        engine.submit(base, max_new_tokens=1, warmup=True)
        engine.run()
        for n in dict.fromkeys(min(s, cap - bs) for s in lens):
            if n < 1:
                continue
            suffix = (np.arange(n) + n) % (vocab - 1) + 1
            engine.submit(np.concatenate([base, suffix]),
                          max_new_tokens=1, warmup=True)
            engine.run()
        # drop the warm-up registrations so the measured run's
        # prefix_hits/blocks_shared reflect the WORKLOAD, not warm-up
        engine._prefix.reset(engine.pool)
    if engine.decode_burst > 1:
        # one compiled scan per pow2 burst length the adapter can pick
        n = 1
        while n <= engine.decode_burst:
            engine.warm_burst(n)
            n *= 2


@dataclass
class LoadResult:
    """Aggregate outcome of one load run (seconds / tokens units)."""

    n_requests: int
    wall_seconds: float
    ttft_p50: float
    ttft_p99: float
    ttft_mean: float
    tokens_per_sec: float
    total_tokens: int
    preemptions: int
    engine_steps: int
    rejected: int = 0
    # prefix-cache + fused-burst accounting (this run's deltas):
    # blocks_saved == prefix_blocks_shared — every shared block is one
    # physical block NOT duplicated and block_size prefill tokens NOT
    # recomputed; prefill_tokens is what the engine actually prefilled
    # (compare against a cold-cache run to see the reduction)
    prefix_hits: int = 0
    prefix_blocks_shared: int = 0
    cow_copies: int = 0
    prefill_tokens: int = 0
    host_roundtrips: int = 0
    burst_tokens: int = 0
    requests: List = field(default_factory=list, repr=False)

    def to_dict(self) -> dict:
        return {
            "n_requests": self.n_requests,
            "wall_seconds": round(self.wall_seconds, 4),
            "ttft_p50_seconds": round(self.ttft_p50, 5),
            "ttft_p99_seconds": round(self.ttft_p99, 5),
            "ttft_mean_seconds": round(self.ttft_mean, 5),
            "tokens_per_sec": round(self.tokens_per_sec, 2),
            "total_tokens": self.total_tokens,
            "preemptions": self.preemptions,
            "engine_steps": self.engine_steps,
            "rejected": self.rejected,
            "prefix_hits": self.prefix_hits,
            "prefix_blocks_shared": self.prefix_blocks_shared,
            "blocks_saved": self.prefix_blocks_shared,
            "cow_copies": self.cow_copies,
            "prefill_tokens": self.prefill_tokens,
            "host_roundtrips": self.host_roundtrips,
            "burst_tokens": self.burst_tokens,
        }


def run_load(engine: ServeEngine, *, rate: float = 50.0,
             n_requests: int = 32, prompt_len=(4, 24),
             max_new=(4, 24), vocab_size: int | None = None,
             eos_token_id=None, temperature: float = 0.0,
             seed: int = 0, max_steps: int = 1_000_000,
             clock=None, shared_prefix_tokens: int = 0,
             shared_prefix_frac: float = 0.0) -> LoadResult:
    """Drive ``engine`` with Poisson traffic and return latency stats.

    Arrival times are pre-drawn (cumsum of Exp(1/rate) gaps) and each
    request is submitted the first time the wall clock passes its
    arrival; between arrivals the engine keeps stepping whatever is
    admitted. Prompt and output lengths are uniform over the given
    inclusive ranges. Returns exact (sample-based) p50/p99 TTFT —
    the ``serve.ttft_seconds`` histogram the engine records carries
    the same data in bucketed form for the metrics roll-up.

    ``clock`` is an object with ``time() -> seconds`` and
    ``sleep(seconds)`` (default: real wall clock). Deterministic runs
    pass an ``observability.FakeClock`` — ideally the same instance
    the engine was built with, so arrivals and TTFTs share a timeline.

    ``shared_prefix_tokens``/``shared_prefix_frac`` model the
    shared-system-prompt workload: a fraction of requests prepend ONE
    synthetic ``shared_prefix_tokens``-long prefix (drawn once per run)
    to their random prompt. Against a prefix-cache engine, every such
    request after the first mounts the prefix's full blocks instead of
    re-prefilling them — the result's ``prefix_blocks_shared`` /
    ``prefill_tokens`` quantify the saving.
    """
    clk = clock if clock is not None else _WallClock()
    if vocab_size is None:
        vocab_size = int(engine._arrays["embed"].shape[0])
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    prompts = [rng.integers(1, vocab_size,
                            size=rng.integers(prompt_len[0],
                                              prompt_len[1] + 1))
               for _ in range(n_requests)]
    news = rng.integers(max_new[0], max_new[1] + 1, size=n_requests)
    if shared_prefix_tokens > 0 and shared_prefix_frac > 0.0:
        prefix = rng.integers(1, vocab_size, size=int(shared_prefix_tokens))
        mask = rng.random(n_requests) < shared_prefix_frac
        prompts = [np.concatenate([prefix, p]) if m else p
                   for p, m in zip(prompts, mask)]

    submitted: List = []
    rejected = 0
    steps = 0
    steps0 = _metric_total("serve.decode_steps")
    preempt0 = _metric_total("serve.preemptions")
    base = {name: _metric_total(name) for name in (
        "serve.prefix_hits", "serve.prefix_blocks_shared",
        "serve.cow_copies", "serve.host_roundtrips",
        "serve.burst_tokens")}
    start = clk.time()
    i = 0
    while i < n_requests or engine.has_work:
        now = clk.time() - start
        while i < n_requests and arrivals[i] <= now:
            try:
                submitted.append(engine.submit(
                    prompts[i], max_new_tokens=int(news[i]),
                    eos_token_id=eos_token_id, temperature=temperature))
            except ValueError:
                # never-runnable under THIS engine's limits (a
                # deliberately tiny --num_blocks pool, a max_seq_len
                # shorter than the draw range): a real front door
                # returns 4xx and keeps serving — count it, keep going
                rejected += 1
            i += 1
        if engine.has_work:
            engine.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"run_load: exceeded max_steps={max_steps} with "
                    f"{len(engine.queue)} queued and {engine.n_active} "
                    f"active — the engine is not making progress")
        elif i < n_requests:
            clk.sleep(min(max(arrivals[i] - now, 0.0), 0.005))
    wall = clk.time() - start

    ttfts = np.array([r.ttft for r in submitted
                      if r.ttft is not None], np.float64)
    total_tokens = int(sum(r.n_generated for r in submitted))
    tps = total_tokens / wall if wall > 0 else 0.0
    _engine_mod._M_TOKENS_PER_SEC.set(round(tps, 2), engine=engine.name)

    def pct(q):
        return float(np.percentile(ttfts, q)) if ttfts.size else 0.0

    return LoadResult(
        n_requests=n_requests,
        wall_seconds=wall,
        ttft_p50=pct(50),
        ttft_p99=pct(99),
        ttft_mean=float(ttfts.mean()) if ttfts.size else 0.0,
        tokens_per_sec=tps,
        total_tokens=total_tokens,
        preemptions=_metric_total("serve.preemptions") - preempt0,
        engine_steps=_metric_total("serve.decode_steps") - steps0,
        rejected=rejected,
        prefix_hits=_metric_total("serve.prefix_hits") - base[
            "serve.prefix_hits"],
        prefix_blocks_shared=_metric_total(
            "serve.prefix_blocks_shared") - base[
                "serve.prefix_blocks_shared"],
        cow_copies=_metric_total("serve.cow_copies") - base[
            "serve.cow_copies"],
        prefill_tokens=int(sum(r.prefilled_tokens for r in submitted)),
        host_roundtrips=_metric_total("serve.host_roundtrips") - base[
            "serve.host_roundtrips"],
        burst_tokens=_metric_total("serve.burst_tokens") - base[
            "serve.burst_tokens"],
        requests=submitted,
    )


def _metric_total(name: str) -> int:
    from .. import observability as obs

    m = obs.registry.get(name)
    return int(m.total()) if m is not None else 0
