"""Continuous-batching serving engine over the paged KV block pool.

The PagedAttention/vLLM (Kwon et al., 2023) + Orca iteration-level
scheduling (Yu et al., 2022) design, adapted to this repo's single-jit
decode architecture: ``models/generation.py`` gives you ONE batched
``generate`` call; this engine gives you a *server* — concurrent
streams that arrive, decode and finish independently while sharing one
fixed-shape compiled decode step and one paged KV pool.

Scheduling policy (the contract the tests pin):

- **Admission: FIFO.** ``submit()`` validates loudly (a request whose
  ``prompt + max_new_tokens`` exceeds ``max_seq_len``, or whose KV
  working set can never fit the pool, raises ``ValueError`` at submit
  time — it could never run) and appends to the queue. Each ``step()``
  admits from the queue head into free decode slots while the pool has
  blocks for the prompt; the head blocks the line (no skip-ahead), so
  admission order is completion-independent.
- **Continuous batching.** A finished stream frees its slot and blocks
  at the step it finishes; the next queued request prefills into that
  slot on the following ``step()`` while the other streams keep
  decoding — there is no batch barrier.
- **Eviction (preemption): youngest-first.** When a growing stream
  needs a KV block and the pool is empty, the most recently admitted
  active stream is evicted (a stream that is itself the youngest
  self-preempts): its blocks return to the pool and the request is
  re-queued at the FRONT with its generated tokens intact (on
  re-admission it re-prefills prompt+generated — vLLM's recompute
  strategy). The oldest stream is never a victim, so it always runs
  to completion and the engine cannot livelock.
- **One persistent compiled decode step.** Slot state (tokens, lengths,
  block tables, active mask, temperatures) rides as jit *data* at fixed
  ``[max_slots, ...]`` shapes, so admission/finish/preemption churn
  never retraces: ``serve.decode_traces`` stays at 1 for the life of
  the engine (the e2e test asserts exactly that). Prefill compiles once
  per power-of-two length bucket.

Attention reads the pool through
``ops/pallas/paged_attention.paged_attention_decode`` — the decode-
specialized Pallas kernel on TPU, its jnp gather reference on CPU — and
the per-layer norm/FFN math is imported from ``models/generation.py``'s
shared helpers, so engine streams and ``generate()`` cannot drift.

Telemetry: the ``serve.`` metric subsystem (claimed in
``observability.metrics.CLAIMED_SUBSYSTEMS``, label discipline audited
by ``tools/lint_registry.py``): queue depth, TTFT, tokens/sec,
preemptions, pool occupancy, batch fill ratio, per-step timings.

Per-request attribution rides on top of the aggregates:
``ServeEngine(trace=True)`` (or ``PADDLE_TPU_TRACE=1``) attaches an
``observability.tracing.ServeTracer`` whose host-side hooks — called
only from the scheduler path, never inside a compiled step, so
``serve.decode_traces`` stays at 1 — grow a span tree on every request
(queue -> prefill -> decode -> preempt -> resume -> recompute).
``ServeEngine(slo=[...])`` (or ``PADDLE_TPU_SLO``) adds an
``observability.slo.SloMonitor`` evaluated at every step boundary.
Both, plus all request timestamps, read the injectable ``clock``
(default ``time.perf_counter``) so load tests can run on a fake clock.
"""
from __future__ import annotations

import collections
import os
import time
from dataclasses import dataclass, field
from typing import Deque, List, Optional

import numpy as np

from .. import observability as obs
from ..core.tensor import Tensor
from ..models import generation as _gen
from .pool import BlockPool, PoolExhaustedError
from .prefix import PrefixCache

__all__ = ["ServeEngine", "Request", "PoolExhaustedError"]

# --- serve. metric subsystem (prefix claimed in CLAIMED_SUBSYSTEMS) ----
_M_QUEUE_DEPTH = obs.gauge(
    "serve.queue_depth", "requests waiting for a decode slot")
_M_POOL_OCCUPANCY = obs.gauge(
    "serve.pool_occupancy", "fraction of KV pool blocks allocated")
_M_BATCH_FILL = obs.gauge(
    "serve.batch_fill", "active streams / max_slots at the last step")
_M_TOKENS_PER_SEC = obs.gauge(
    "serve.tokens_per_sec", "aggregate generated tokens/sec over run()")
_M_ADMITTED = obs.counter(
    "serve.requests_admitted", "requests scheduled into a decode slot "
    "(re-admissions after preemption count again)")
_M_FINISHED = obs.counter(
    "serve.requests_finished", "requests completed, by reason "
    "(eos / max_new_tokens)")
_M_REJECTED = obs.counter(
    "serve.requests_rejected", "submissions refused at validation, by "
    "reason")
_M_PREEMPTIONS = obs.counter(
    "serve.preemptions", "streams evicted mid-decode, by reason")
_M_STALLS = obs.counter(
    "serve.admission_stalls", "scheduler passes where the queue head "
    "could not be admitted, by reason")
_M_TOKENS = obs.counter(
    "serve.tokens_generated", "tokens emitted across all streams")
_M_DECODE_STEPS = obs.counter(
    "serve.decode_steps", "batched decode steps executed")
_M_DECODE_TRACES = obs.counter(
    "serve.decode_traces", "times the persistent decode step was "
    "traced — slot churn must keep this at 1 per engine")
_M_PREFILL_TRACES = obs.counter(
    "serve.prefill_traces", "prefill compiles, by length bucket")
_M_TTFT = obs.histogram(
    "serve.ttft_seconds", "submit -> first generated token wall time "
    "(queue wait included)")
_M_REQUEST_SECONDS = obs.histogram(
    "serve.request_seconds", "submit -> finish wall time per request")
_M_DECODE_SECONDS = obs.histogram(
    "serve.decode_step_seconds", "wall time of one batched decode step")
_M_PREFILL_SECONDS = obs.histogram(
    "serve.prefill_seconds", "wall time of one prefill call")
_M_PREFIX_HITS = obs.counter(
    "serve.prefix_hits", "admissions that mounted shared KV blocks "
    "from the prefix cache")
_M_PREFIX_BLOCKS = obs.counter(
    "serve.prefix_blocks_shared", "full KV blocks mounted read-only "
    "from the prefix cache at admission — prefill was skipped for "
    "those tokens")
_M_COW = obs.counter(
    "serve.cow_copies", "copy-on-write block duplications where a "
    "stream diverged inside a shared prefix block")
_M_BURST_TOKENS = obs.counter(
    "serve.burst_tokens", "tokens generated inside fused multi-step "
    "decode bursts (the on-chip lax.scan path)")
_M_HOST_RT = obs.counter(
    "serve.host_roundtrips", "host->device decode dispatches — one "
    "per burst, so decode_burst=N cuts this ~N x per token")

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"


@dataclass
class Request:
    """One stream: prompt in, tokens out, scheduling state in between."""

    id: int
    prompt: np.ndarray                     # [t0] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    temperature: float = 0.0               # 0.0 = greedy
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    finish_reason: Optional[str] = None
    state: str = QUEUED
    ids: List[int] = field(default_factory=list)   # prompt + generated
    blocks: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    admit_seq: int = -1                    # recency rank for eviction
    preemptions: int = 0
    warmup: bool = False                   # excluded from TTFT telemetry
    # prefix-cache bookkeeping: trie registration cursor, how many full
    # blocks of ids are already covered by the trie, and sharing stats
    # (blocks mounted from the cache at the LAST admission; tokens this
    # request actually prefilled across all admissions — suffix-only
    # when the cache hit)
    prefix_node: Optional[object] = field(default=None, repr=False)
    registered_upto: int = 0
    shared_blocks: int = 0
    prefilled_tokens: int = 0
    # span tree (observability.tracing.RequestTrace) when the engine
    # runs with tracing enabled; None otherwise
    trace: Optional[object] = field(default=None, repr=False)

    @property
    def n_prompt(self) -> int:
        return len(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.ids) - self.n_prompt

    @property
    def output_ids(self) -> List[int]:
        """Generated tokens only (prompt excluded)."""
        return self.ids[self.n_prompt:]

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time


class ServeEngine:
    """Continuous-batching server over a paged KV pool (module docstring
    has the admission/eviction contract). Llama and GPT families.

    Usage::

        eng = ServeEngine(model, max_slots=4, block_size=32,
                          num_blocks=64, max_seq_len=256)
        r1 = eng.submit(prompt_ids, max_new_tokens=32, eos_token_id=2)
        r2 = eng.submit(other_ids, max_new_tokens=64)
        eng.run()                      # or step() from your own loop
        print(r1.output_ids, r1.ttft)
    """

    def __init__(self, model, *, max_slots: int = 4, block_size: int = 32,
                 num_blocks: int = 64, max_seq_len: int = 256,
                 seed: int = 0, name: str = "default",
                 attention_backend: str = "auto", clock=None,
                 trace=None, slo=None, prefix_cache=None,
                 decode_burst=None):
        """``clock`` is a zero-arg callable returning seconds (default
        ``time.perf_counter``) — every request timestamp, tracer span
        and SLO window reads it, so tests inject a fake. ``trace`` is
        True/False, a ready ``ServeTracer``, or None to read
        ``PADDLE_TPU_TRACE``. ``slo`` is a rule list (``SloRule``/
        dicts/JSON), a ready ``SloMonitor``, or None to read
        ``PADDLE_TPU_SLO``. ``prefix_cache`` is True/False or None to
        read ``PADDLE_TPU_PREFIX_CACHE`` (cross-request KV block
        sharing — see ``serve/prefix.py``). ``decode_burst`` is the
        max number of decode steps fused into one on-chip ``lax.scan``
        dispatch (None reads ``PADDLE_TPU_DECODE_BURST``, default 1 =
        the PR-14 one-roundtrip-per-token loop)."""
        import jax

        if not hasattr(model, "llama") and not hasattr(model, "gpt"):
            raise NotImplementedError(
                "ServeEngine supports the Llama and GPT families (the "
                "paged-decode surface); MoE models decode on the dense "
                f"path — got {type(model).__name__}")
        self._is_llama = hasattr(model, "llama")
        p, _fwd = _gen._decode_family(model)
        max_pos = p.get("max_positions")
        if max_pos is not None and max_seq_len > max_pos:
            raise ValueError(
                f"max_seq_len ({max_seq_len}) exceeds the model's "
                f"learned position table (max_position_embeddings="
                f"{max_pos})")
        if max_slots < 1:
            raise ValueError(
                f"max_slots must be >= 1, got {max_slots} — with no "
                f"decode slot nothing can ever be admitted and every "
                f"driver loop would spin forever")
        self.name = str(name)
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self._clock = clock if clock is not None else time.perf_counter
        self.max_blocks_per_seq = -(-self.max_seq_len // self.block_size)
        self.pool = BlockPool(num_blocks, block_size)
        self._backend = attention_backend

        self._static = {k: v for k, v in p.items()
                        if not hasattr(v, "dtype")
                        and not isinstance(v, list)}
        self._arrays = {k: v for k, v in p.items() if k not in self._static}
        self._nh, self._nkv = p["nh"], p["nkv"]
        self._dh, self._L = p["dh"], len(p["layers"])
        self._dtype = p["embed"].dtype
        import jax.numpy as jnp

        self._caches = [
            (jnp.zeros((self._nkv, self.pool.num_blocks, self.block_size,
                        self._dh), self._dtype),
             jnp.zeros((self._nkv, self.pool.num_blocks, self.block_size,
                        self._dh), self._dtype))
            for _ in range(self._L)]

        # host-side slot state (jit DATA — shapes never change)
        self._slots: List[Optional[Request]] = [None] * self.max_slots
        self._tables = np.zeros(
            (self.max_slots, self.max_blocks_per_seq), np.int32)
        self._lens = np.zeros(self.max_slots, np.int32)
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._temps = np.zeros(self.max_slots, np.float32)
        # per-slot eos ids (-1 = none) ride into the fused burst so eos
        # latching can happen inside the scan
        self._eos = np.full(self.max_slots, -1, np.int32)

        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "PADDLE_TPU_PREFIX_CACHE", "").strip().lower() in (
                    "1", "true", "yes", "on")
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(self.block_size) if prefix_cache else None)
        if decode_burst is None:
            decode_burst = int(
                os.environ.get("PADDLE_TPU_DECODE_BURST", "").strip()
                or 1)
        if int(decode_burst) < 1:
            raise ValueError(
                f"decode_burst must be >= 1, got {decode_burst}")
        self.decode_burst = int(decode_burst)
        # pow2 burst lengths actually dispatched — each is one compiled
        # scan, so serve.decode_traces == len(burst_lens_used) in burst
        # mode (the bounded-trace contract the tests pin)
        self.burst_lens_used: set = set()

        self.queue: Deque[Request] = collections.deque()
        self.finished: List[Request] = []
        self.decode_traces = 0
        self.prefill_traces = 0
        self._next_id = 0
        self._admit_counter = 0
        # lifetime totals the step-boundary SLO evaluation differences
        self._n_tokens = 0
        self._n_preempts = 0
        self._key = jax.random.PRNGKey(seed)
        self._rng = np.random.default_rng(seed)
        # the caches are DONATED (argument 1 after the bound self):
        # the engine replaces self._caches with the returned pool every
        # call, so in-place aliasing is safe — and without it every
        # decode tick would COPY the entire pool (≈1 GB/token at the
        # 10-layer/96x128-block bf16 serving shape)
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._prefill_fn = jax.jit(self._prefill_impl,
                                   donate_argnums=(1,))
        # prefix-cache companions: suffix prefill (attends through the
        # block table so suffix tokens see the shared resident prefix)
        # and the copy-on-write block duplication; fused decode burst
        # (n static -> one trace per pow2 burst length)
        self._suffix_prefill_fn = jax.jit(self._suffix_prefill_impl,
                                          donate_argnums=(1,))
        self._cow_fn = jax.jit(self._cow_impl, donate_argnums=(0,))
        self._burst_fn = jax.jit(self._burst_impl, static_argnums=(0,),
                                 donate_argnums=(2,))

        # request-lifecycle tracing + SLO guardrails (both host-side
        # scheduler-path bookkeeping; the compiled steps never see them)
        from ..observability import slo as _slo_mod
        from ..observability import tracing as _tracing_mod

        if trace is None:
            trace = _tracing_mod.trace_enabled_from_env()
        if isinstance(trace, _tracing_mod.ServeTracer):
            self.tracer: Optional[_tracing_mod.ServeTracer] = trace
        elif trace:
            self.tracer = _tracing_mod.ServeTracer(
                self.name, self._clock, max_slots=self.max_slots)
        else:
            self.tracer = None
        if slo is None:
            slo = _slo_mod.rules_from_env() or None
        if isinstance(slo, _slo_mod.SloMonitor):
            self.slo: Optional[_slo_mod.SloMonitor] = slo
        elif slo:
            self.slo = _slo_mod.SloMonitor(
                slo, engine=self.name, clock=self._clock,
                exemplars=(self.tracer.exemplars if self.tracer
                           else None))
        else:
            self.slo = None

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0,
               warmup: bool = False) -> Request:
        """Validate and enqueue one stream (FIFO). Raises ``ValueError``
        for requests that could NEVER run — too long for
        ``max_seq_len``, or a KV working set larger than the whole pool
        — instead of failing later with a corrupted gather. A request
        that merely has to WAIT for blocks is queued, not refused.
        ``warmup`` marks a compile-warming request whose TTFT (which
        bills the XLA compile, not serving latency) must stay out of
        the ``serve.ttft_seconds`` histogram."""
        if isinstance(prompt, Tensor):
            prompt = np.asarray(prompt._value)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            _M_REJECTED.inc(engine=self.name, reason="empty_prompt")
            raise ValueError("submit: prompt is empty")
        if max_new_tokens < 1:
            _M_REJECTED.inc(engine=self.name, reason="bad_max_new_tokens")
            raise ValueError(
                f"submit: max_new_tokens must be >= 1, got "
                f"{max_new_tokens}")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_seq_len:
            _M_REJECTED.inc(engine=self.name, reason="too_long")
            raise ValueError(
                f"submit: prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the engine's "
                f"max_seq_len ({self.max_seq_len})")
        # the last generated token is emitted but never written back,
        # so the KV working set is total - 1 positions
        need = self.pool.blocks_for_tokens(total - 1)
        if need > self.pool.num_blocks:
            _M_REJECTED.inc(engine=self.name, reason="pool_too_small")
            raise ValueError(
                f"submit: request needs {need} KV blocks "
                f"(block_size={self.block_size}) but the whole pool is "
                f"{self.pool.num_blocks} — it can never be admitted")
        req = Request(
            id=self._next_id, prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_token_id=(None if eos_token_id is None
                          else int(eos_token_id)),
            temperature=float(temperature),
            submit_time=self._clock(),
            ids=[int(t) for t in prompt], warmup=bool(warmup))
        self._next_id += 1
        self.queue.append(req)
        if self.tracer is not None and not req.warmup:
            self.tracer.on_submit(req)
        _M_QUEUE_DEPTH.set(len(self.queue), engine=self.name)
        return req

    # -- engine loop -------------------------------------------------------
    @property
    def n_active(self) -> int:
        """Streams currently holding a decode slot."""
        return sum(1 for r in self._slots if r is not None)

    @property
    def has_work(self) -> bool:
        """True while anything is queued or decoding."""
        return bool(self.queue) or any(r is not None for r in self._slots)

    def step(self) -> int:
        """One scheduler iteration: admit from the queue into free
        slots (prefill), then run ONE batched decode step for every
        active stream, retiring the ones that finish. Returns the
        number of streams that were active this step."""
        serving_real_work = self.slo is not None and any(
            not r.warmup for r in self._live_requests())
        tok0, pre0 = self._n_tokens, self._n_preempts
        self._admit()
        n_active = self.n_active
        if n_active:
            if self.decode_burst > 1:
                self._decode_burst_once()
            else:
                self._decode_once()
        _M_QUEUE_DEPTH.set(len(self.queue), engine=self.name)
        _M_POOL_OCCUPANCY.set(round(self.pool.occupancy, 4),
                              engine=self.name)
        _M_BATCH_FILL.set(round(n_active / self.max_slots, 4),
                          engine=self.name)
        if serving_real_work:
            # step-boundary SLO evaluation — skipped while the only
            # work is compile-warming (whose throughput/TTFT would
            # bill XLA, not serving)
            self.slo.on_step(tokens=self._n_tokens - tok0,
                             preemptions=self._n_preempts - pre0,
                             now=self._clock())
        obs.health.maybe_on_step(self._clock())
        return n_active

    def _live_requests(self):
        for r in self.queue:
            yield r
        for r in self._slots:
            if r is not None:
                yield r

    def run(self, max_steps: int = 100_000) -> List[Request]:
        """Drive :meth:`step` until queue and slots drain; returns the
        finished requests. Sets ``serve.tokens_per_sec`` over the run."""
        t0 = self._clock()
        tok0 = sum(r.n_generated for r in self.finished)
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"run(): exceeded max_steps={max_steps} with "
                    f"{len(self.queue)} queued and "
                    f"{sum(1 for r in self._slots if r)} active — "
                    f"scheduler is not making progress")
        dt = self._clock() - t0
        n_tok = sum(r.n_generated for r in self.finished) - tok0
        if dt > 0 and n_tok:
            _M_TOKENS_PER_SEC.set(round(n_tok / dt, 2), engine=self.name)
        return self.finished

    # -- scheduling --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return None

    def _admit(self):
        """FIFO admission from the queue head into free slots. With the
        prefix cache on, the queue head's prompt is longest-prefix
        matched against resident full blocks first: matched blocks are
        acquired (refcount +1) and mounted directly into the block
        table, and prefill runs only on the unshared suffix — the TTFT
        win. A prompt whose EVERY full block matches still recomputes
        its last token (the logits source) into a copy-on-write
        duplicate of the final matched block, so no stream ever writes
        KV that another stream reads."""
        bs = self.block_size
        while self.queue:
            slot = self._free_slot()
            if slot is None:
                _M_STALLS.inc(engine=self.name, reason="no_free_slot")
                return
            req = self.queue[0]
            # resumed streams re-prefill prompt+generated minus the
            # pending last token; fresh streams prefill the prompt.
            # COPY either way: _prefill appends the first sampled token
            # to req.ids, and an aliased list would inflate the slot
            # length by one (skipping a cache slot + shifting rope)
            prefill_ids = list(req.ids[:-1] if req.n_generated > 0
                               else req.ids)
            n_pre = len(prefill_ids)
            matched: List[int] = []
            cow = False
            if self._prefix is not None:
                matched = self._prefix.match(prefill_ids)
                # a full-prompt match (every token in matched full
                # blocks) must still produce the last token's logits:
                # recompute it into a CoW copy of the last block
                cow = bool(matched) and len(matched) * bs >= n_pre
            read_only = matched[:-1] if cow else matched
            if read_only:
                self.pool.acquire(read_only)
                self._prefix.note_acquired(read_only)
            need = self.pool.blocks_for_tokens(n_pre) - len(read_only)
            evictable = (self._prefix.evictable_blocks
                         if self._prefix is not None else 0)
            if need > self.pool.free_blocks + evictable:
                # head-of-line blocking is the FIFO contract: later
                # (smaller) requests do NOT jump a starving head. Put
                # the acquired prefix references back (registered
                # blocks park in the cached state, still matchable)
                if read_only:
                    self._prefix.note_cached(
                        self.pool.release(read_only, retain=read_only))
                _M_STALLS.inc(engine=self.name, reason="no_free_blocks")
                return
            self.queue.popleft()
            fresh = self._alloc_blocks(need)
            req.blocks = list(read_only) + fresh
            req.shared_blocks = len(read_only)
            if cow:
                # fresh[0] sits at the divergence position: duplicate
                # the shared block's K/V so the recomputed last token
                # writes into private pages
                self._caches = self._cow_fn(
                    self._caches, np.int32(matched[-1]),
                    np.int32(fresh[0]))
                _M_COW.inc(engine=self.name)
            if read_only or cow:
                _M_PREFIX_HITS.inc(engine=self.name)
                _M_PREFIX_BLOCKS.inc(len(read_only), engine=self.name)
            if self._prefix is not None:
                req.prefix_node = self._prefix.node_for(prefill_ids)
                req.registered_upto = len(matched)
            req.slot = slot
            req.state = RUNNING
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self._slots[slot] = req
            row = np.zeros(self.max_blocks_per_seq, np.int32)
            row[:len(req.blocks)] = req.blocks
            self._tables[slot] = row
            if self.tracer is not None:
                self.tracer.on_admit(req, slot,
                                     resumed=req.n_generated > 0)
            # shared tokens are resident KV the suffix attends to but
            # never recomputes; under CoW the suffix is the last token
            start = (n_pre - 1) if cow else len(read_only) * bs
            self._prefill(req, prefill_ids, start=start)
            _M_ADMITTED.inc(engine=self.name)
            if req.state is FINISHED:
                continue        # eos / max_new hit on the first token
            self._lens[slot] = n_pre
            self._tokens[slot] = req.ids[-1]
            self._temps[slot] = req.temperature
            self._eos[slot] = (-1 if req.eos_token_id is None
                               else req.eos_token_id)

    def _alloc_blocks(self, n: int) -> List[int]:
        """Pool alloc with prefix-cache eviction backing it: when the
        free list runs short, reclaim LRU refcount-0 cached blocks
        first (their KV is resident only speculatively); referenced
        blocks are never touched. Raises ``PoolExhaustedError`` when
        even eviction cannot cover ``n``."""
        if self._prefix is not None and n > self.pool.free_blocks:
            self._prefix.evict(self.pool, n - self.pool.free_blocks)
        return self.pool.alloc(n)

    def _prefill(self, req: Request, prefill_ids: List[int],
                 start: int = 0):
        """Prefill this stream's KV. ``start`` tokens are already
        resident (mounted from the prefix cache), so only the suffix
        ``prefill_ids[start:]`` is computed — through the block table,
        where each suffix row attends to the shared prefix it never
        recomputed. ``start == 0`` is the cold path (in-prompt causal
        attention, the PR-14 kernel)."""
        import jax.numpy as jnp

        suffix = prefill_ids[start:]
        n = len(suffix)
        bucket = max(8, 1 << (n - 1).bit_length())   # pow2 length buckets
        bucket = min(bucket, self.max_seq_len)
        if self.tracer is not None:
            self.tracer.on_prefill(req, bucket=bucket, tokens=n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = suffix
        req.prefilled_tokens += n
        with _M_PREFILL_SECONDS.time(engine=self.name):
            if start == 0:
                self._caches, logits = self._prefill_fn(
                    self._arrays, self._caches, jnp.asarray(padded),
                    jnp.int32(n), jnp.asarray(self._tables[req.slot]))
            else:
                self._caches, logits = self._suffix_prefill_fn(
                    self._arrays, self._caches, jnp.asarray(padded),
                    jnp.int32(n), jnp.int32(start),
                    jnp.asarray(self._tables[req.slot]))
        if req.n_generated == 0:
            # fresh stream: its FIRST token comes from the prefill
            # logits (this is the TTFT moment); resumed streams already
            # hold their pending token, the logits are discarded
            tok = self._sample_host(np.asarray(logits), req.temperature)
            now = self._clock()
            req.first_token_time = now
            if not req.warmup:
                _M_TTFT.observe(now - req.submit_time, engine=self.name)
                if self.slo is not None:
                    self.slo.observe_ttft(now - req.submit_time, now=now)
            if self.tracer is not None:
                self.tracer.on_first_token(req, now)
            self._append_token(req, tok)
        else:
            # resumed streams append nothing here; their just-refilled
            # full blocks still need trie registration
            self._register_full_blocks(req)
        if self.tracer is not None and req.state is not FINISHED:
            self.tracer.on_decode_begin(req)

    def _sample_host(self, logits: np.ndarray, temperature: float) -> int:
        """First-token sampling (host-side; decode steps sample on
        device). Greedy at temperature 0."""
        if temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        z -= z.max()
        prob = np.exp(z)
        prob /= prob.sum()
        return int(self._rng.choice(logits.shape[0], p=prob))

    def _register_full_blocks(self, req: Request):
        """Register every newly-FULL block of this stream in the prefix
        trie so later prompts can share it. Written positions are
        ``len(ids) - 1`` (the pending last token is emitted but not yet
        written); a chunk another stream registered first wins and this
        stream's block simply stays private."""
        if self._prefix is None or req.prefix_node is None:
            return
        bs = self.block_size
        full = (len(req.ids) - 1) // bs
        while req.registered_upto < full:
            b = req.registered_upto
            req.prefix_node = self._prefix.register(
                req.prefix_node, req.ids[b * bs:(b + 1) * bs],
                req.blocks[b])
            req.registered_upto += 1

    def _release_blocks(self, req: Request):
        """Drop this stream's references. Trie-registered blocks whose
        refcount hits 0 are RETAINED in the pool's cached state (their
        KV stays matchable — this is what makes preemption recompute
        and repeat system prompts nearly free); everything else returns
        to the free list."""
        if self._prefix is not None:
            retain = [b for b in req.blocks
                      if self._prefix.is_registered(b)]
            self._prefix.note_cached(
                self.pool.release(req.blocks, retain=retain))
        else:
            self.pool.free(req.blocks)
        req.blocks = []

    def _append_token(self, req: Request, tok: int,
                      now: Optional[float] = None):
        """``now`` carries the in-scan step-boundary timestamp when the
        token was produced inside a fused burst (interpolated between
        the burst's host dispatch and return); None = read the clock."""
        req.ids.append(int(tok))
        self._n_tokens += 1
        _M_TOKENS.inc(engine=self.name)
        self._register_full_blocks(req)
        if req.eos_token_id is not None and tok == req.eos_token_id:
            self._finish(req, "eos", now=now)
        elif req.n_generated >= req.max_new_tokens:
            self._finish(req, "max_new_tokens", now=now)

    def _finish(self, req: Request, reason: str,
                now: Optional[float] = None):
        self._release_blocks(req)
        if req.slot is not None:
            self._clear_slot(req.slot)
        req.slot = None
        req.state = FINISHED
        req.finish_reason = reason
        req.finish_time = self._clock() if now is None else now
        self.finished.append(req)
        _M_FINISHED.inc(engine=self.name, reason=reason)
        _M_REQUEST_SECONDS.observe(req.finish_time - req.submit_time,
                                   engine=self.name)
        if self.tracer is not None:
            self.tracer.on_finish(req)

    def _clear_slot(self, slot: int):
        self._slots[slot] = None
        self._tables[slot] = 0
        self._lens[slot] = 0
        self._tokens[slot] = 0
        self._temps[slot] = 0.0
        self._eos[slot] = -1

    def _preempt_youngest(self) -> Request:
        """Evict the most recently admitted active stream; its blocks
        return to the pool and the request goes back to the FRONT of
        the queue (re-prefill of prompt+generated on re-admission).
        The OLDEST stream is therefore never a victim and always runs
        to completion — the no-livelock guarantee."""
        victims = [r for r in self._slots if r is not None]
        victim = max(victims, key=lambda r: r.admit_seq)
        self._release_blocks(victim)
        self._clear_slot(victim.slot)
        victim.slot = None
        victim.state = QUEUED
        victim.preemptions += 1
        self._n_preempts += 1
        self.queue.appendleft(victim)
        _M_PREEMPTIONS.inc(engine=self.name, reason="pool_exhausted")
        if self.tracer is not None:
            self.tracer.on_preempt(victim)
        return victim

    def _ensure_blocks(self, lookahead: int = 1):
        """Every active stream needs the block its next token writes
        into; allocate at block boundaries, evicting youngest-first
        when the pool runs dry (a stream that is ITSELF the youngest
        self-preempts back to the queue rather than evicting an older
        one).

        ``lookahead > 1`` (the fused-burst path) pre-allocates enough
        blocks for the next ``lookahead`` tokens so a stream one token
        shy of a block edge doesn't collapse the whole batch's burst to
        one step. Only the MUST-HAVE block (the one the very next token
        writes into) is worth preempting for — when the pool can't fund
        the extra lookahead blocks the burst just shrinks via
        ``_pick_burst_len``'s capacity term."""
        for req in sorted((r for r in self._slots if r is not None),
                          key=lambda r: r.admit_seq):
            if req.slot is None:
                continue          # evicted by an older stream this pass
            la = max(1, min(lookahead,
                            req.max_new_tokens - req.n_generated))
            bi = int(self._lens[req.slot]) // self.block_size
            target = (int(self._lens[req.slot]) + la - 1) // self.block_size
            while target >= len(req.blocks):
                try:
                    new = self._alloc_blocks(1)
                except PoolExhaustedError:
                    if len(req.blocks) > bi:
                        break     # next token covered; burst shrinks
                    if self._preempt_youngest() is req:
                        break     # req went back to the queue itself
                    continue
                req.blocks.extend(new)
                self._tables[req.slot, len(req.blocks) - 1] = new[0]

    def _decode_once(self):
        import jax
        import jax.numpy as jnp

        self._ensure_blocks()
        active_np = np.array([r is not None for r in self._slots], bool)
        if not active_np.any():
            return                # everyone was preempted away
        self._key, sub = jax.random.split(self._key)
        t0 = self._clock()
        with _M_DECODE_SECONDS.time(engine=self.name):
            nxt, self._caches = self._decode_fn(
                self._arrays, self._caches, jnp.asarray(self._tokens),
                jnp.asarray(self._lens), jnp.asarray(active_np),
                jnp.asarray(self._tables), jnp.asarray(self._temps), sub)
            nxt = np.asarray(nxt)
        t1 = self._clock()
        _M_DECODE_STEPS.inc(engine=self.name)
        _M_HOST_RT.inc(engine=self.name)
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            self._lens[slot] += 1
            self._append_token(req, int(nxt[slot]))
            if req.state is not FINISHED:
                self._tokens[slot] = req.ids[-1]
        if self.tracer is not None:
            # active_after = runnable slots LEFT BEHIND by this step —
            # the gap to the next step only counts as host-side stall
            # (PTL404) when someone was still waiting to decode
            self.tracer.on_decode_step(t0, t1,
                                       active_after=self.n_active,
                                       queued=len(self.queue))

    def _pick_burst_len(self) -> int:
        """Adaptive burst length: never cross a block boundary (the
        scheduler allocates blocks host-side) or any stream's
        max-length mid-burst, then round DOWN to a power of two so the
        number of compiled scans stays bounded at one per pow2 bucket
        (``serve.decode_traces == len(burst_lens_used)``)."""
        n = self.decode_burst
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            cap = len(req.blocks) * self.block_size - int(
                self._lens[slot])
            n = min(n, cap, req.max_new_tokens - req.n_generated)
        n = max(1, n)
        return 1 << (n.bit_length() - 1)

    def _decode_burst_once(self):
        """One scheduler pass's worth of decode as a fused burst: N
        decode ticks execute as ONE compiled ``lax.scan`` dispatch that
        never leaves the chip (sampling, eos latching and length
        advance all in-scan), then the host replays the emitted token
        matrix through the normal finish/registration bookkeeping.
        Per-token timestamps are the in-scan step boundaries
        (interpolated across the dispatch window, indexed by the
        per-slot emit counts carried out of the scan) — NOT the
        burst-end host time, so TTFT/latency attribution matches the
        unbursted engine to within one step."""
        import jax
        import jax.numpy as jnp

        self._ensure_blocks(lookahead=self.decode_burst)
        active_np = np.array([r is not None for r in self._slots], bool)
        if not active_np.any():
            return                # everyone was preempted away
        n = self._pick_burst_len()
        self.burst_lens_used.add(n)
        # pre-split the SAME per-step key schedule the unbursted loop
        # draws, so burst=N and burst=1 sample identical streams
        subs = []
        for _ in range(n):
            self._key, sub = jax.random.split(self._key)
            subs.append(sub)
        t0 = self._clock()
        with _M_DECODE_SECONDS.time(engine=self.name):
            ys, emitted, self._caches = self._burst_fn(
                n, self._arrays, self._caches,
                jnp.asarray(self._tokens), jnp.asarray(self._lens),
                jnp.asarray(active_np), jnp.asarray(self._tables),
                jnp.asarray(self._temps), jnp.asarray(self._eos),
                jnp.stack(subs))
            ys = np.asarray(ys)
            emitted = np.asarray(emitted)
        t1 = self._clock()
        _M_DECODE_STEPS.inc(n, engine=self.name)
        _M_HOST_RT.inc(engine=self.name)
        per_step = (t1 - t0) / n
        n_emitted = 0
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            for j in range(int(emitted[slot])):
                self._lens[slot] += 1
                n_emitted += 1
                self._append_token(req, int(ys[j, slot]),
                                   now=t0 + per_step * (j + 1))
                if req.state is FINISHED:
                    break
            if req.state is not FINISHED:
                self._tokens[slot] = req.ids[-1]
        _M_BURST_TOKENS.inc(n_emitted, engine=self.name)
        if self.tracer is not None:
            self.tracer.on_decode_step(t0, t1,
                                       active_after=self.n_active,
                                       queued=len(self.queue),
                                       tokens=n)

    def warm_burst(self, n: int):
        """Compile the ``n``-step fused burst against idle slot state
        (every row inactive: KV writes fence off the pool, outputs are
        discarded) so serving traffic never pays the XLA compile."""
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(jax.random.PRNGKey(0), n)
        _, _, self._caches = self._burst_fn(
            int(n), self._arrays, self._caches,
            jnp.asarray(self._tokens), jnp.asarray(self._lens),
            jnp.zeros(self.max_slots, bool), jnp.asarray(self._tables),
            jnp.asarray(self._temps), jnp.asarray(self._eos), keys)

    # -- compiled steps ----------------------------------------------------
    def _scatter_kv(self, kc, vc, k_new, v_new, safe_slot):
        """Write per-row K/V ([rows, kvh, dh]) into the pool at flat
        slot ids (out-of-range ids drop — that is how inactive slots
        and pad rows are fenced off the pool)."""
        nb, bs = self.pool.num_blocks, self.block_size
        kvh, dh = self._nkv, self._dh
        kc_f = kc.reshape(kvh, nb * bs, dh)
        vc_f = vc.reshape(kvh, nb * bs, dh)
        kc_f = kc_f.at[:, safe_slot, :].set(
            k_new.transpose(1, 0, 2), mode="drop")
        vc_f = vc_f.at[:, safe_slot, :].set(
            v_new.transpose(1, 0, 2), mode="drop")
        return (kc_f.reshape(kvh, nb, bs, dh),
                vc_f.reshape(kvh, nb, bs, dh))

    def _rope_rows(self, pos):
        """cos/sin rows at per-row positions ``pos`` — computed ONCE
        per compiled call and reused by every layer (the tables are
        position-only; rebuilding them per layer would stage L
        identical table subgraphs per trace)."""
        import jax.numpy as jnp

        from ..incubate.nn.functional import _rope_tables

        cos_full, sin_full = _rope_tables(
            self.max_seq_len, self._dh, self._static["theta"], True,
            jnp.float32)
        return (jnp.take(cos_full, pos, axis=0)[:, None, :],
                jnp.take(sin_full, pos, axis=0)[:, None, :])

    def _rope(self, q, k, cos, sin):
        """Rotate q/k ([rows, heads, dh]) by precomputed cos/sin rows
        (Llama families only)."""
        import jax.numpy as jnp

        from ..incubate.nn.functional._rope_common import rotate_half

        q = (q.astype(jnp.float32) * cos
             + rotate_half(q.astype(jnp.float32), True) * sin)
        k = (k.astype(jnp.float32) * cos
             + rotate_half(k.astype(jnp.float32), True) * sin)
        return q.astype(self._dtype), k.astype(self._dtype)

    def _stack_layers(self, p, x, rope, caches, safe_slot, attn):
        """ONE transformer stack for BOTH compiled steps: family
        norm/projection, rope, K/V scatter into the pool, attention
        via the provided closure, residual + FFN, final norm. ``x`` is
        [rows, H]; ``attn(q, k, v, kc, vc) -> [rows, nh*dh]`` is the
        only thing decode and prefill legitimately differ in (paged
        pool attention vs in-prompt causal softmax), so it is the only
        thing they provide. Returns (normed hidden [rows, H],
        new caches)."""
        rows = x.shape[0]
        nh, kvh, dh = self._nh, self._nkv, self._dh
        dtype = self._dtype

        new_caches = []
        for lp, (kc, vc) in zip(p["layers"], caches):
            if self._is_llama:
                h = _gen._rms(x, lp["ln1"], p["eps"], dtype)
                q = (h @ lp["wq"]).reshape(rows, nh, dh)
                k = (h @ lp["wk"]).reshape(rows, kvh, dh)
                v = (h @ lp["wv"]).reshape(rows, kvh, dh)
                q, k = self._rope(q, k, *rope)
            else:
                h = _gen._ln(x, lp["ln1_w"], lp["ln1_b"], p["eps"],
                             dtype)
                qkv = (h @ lp["wqkv"] + lp["bqkv"]).reshape(
                    rows, 3, nh, dh)
                q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kc, vc = self._scatter_kv(kc, vc, k, v, safe_slot)
            new_caches.append((kc, vc))
            ctx = attn(q, k, v, kc, vc)
            if self._is_llama:
                x = x + ctx.astype(dtype) @ lp["wo"]
                x = x + _gen._llama_ffn(
                    _gen._rms(x, lp["ln2"], p["eps"], dtype), lp, dtype)
            else:
                x = x + ctx.astype(dtype) @ lp["wo"] + lp["bo"]
                x = x + _gen._gpt_ffn(
                    _gen._ln(x, lp["ln2_w"], lp["ln2_b"], p["eps"],
                             dtype), lp, dtype)
        if self._is_llama:
            return _gen._rms(x, p["norm"], p["eps"], dtype), new_caches
        return (_gen._ln(x, p["normf_w"], p["normf_b"], p["eps"], dtype),
                new_caches)

    def _decode_impl(self, arrays, caches, tokens, lens, active, tables,
                     temps, key):
        """ONE batched decode tick over every slot: write each active
        stream's pending token into its KV block, attend through the
        block tables (decode-specialized paged attention), project,
        sample. Shapes are fixed at [max_slots, ...]; slot churn is
        data, so this traces exactly once per engine (asserted via
        ``serve.decode_traces``). The caches are DONATED: the pool
        updates in place instead of being copied per token."""
        import jax
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import paged_attention_decode

        # executes at TRACE time only — the flatness counter the e2e
        # continuous-batching test pins at 1
        self.decode_traces += 1
        _M_DECODE_TRACES.inc(engine=self.name)
        return self._decode_core(caches, tokens, lens, active, tables,
                                 temps, key, arrays=arrays)

    def _decode_core(self, caches, tokens, lens, active, tables, temps,
                     key, *, arrays=None, p=None):
        """The decode-tick math, shared VERBATIM by the single-step jit
        and every tick of the fused burst scan — op-for-op identity is
        what makes burst=N token-for-token equal to burst=1."""
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import paged_attention_decode

        if p is None:
            p = {**arrays, **self._static}
        b = self.max_slots
        nh = self._nh
        nb, bs = self.pool.num_blocks, self.block_size

        x = jnp.take(p["embed"], tokens, axis=0)          # [B, H]
        pos = lens.astype(jnp.int32)
        rope = None
        if self._is_llama:
            rope = self._rope_rows(pos)
        else:
            x = x + jnp.take(p["wpe"], pos, axis=0)
        lengths = jnp.where(active, pos + 1, 0)
        bi = jnp.clip(pos // bs, 0, self.max_blocks_per_seq - 1)
        phys = jnp.take_along_axis(tables, bi[:, None], axis=1)[:, 0]
        slot = phys * bs + pos % bs
        safe_slot = jnp.where(active, slot, nb * bs)      # OOB drops

        def attn(q, _k, _v, kc, vc):
            return paged_attention_decode(
                q, kc, vc, lengths, tables,
                backend=self._backend).reshape(b, nh * self._dh)

        out, new_caches = self._stack_layers(p, x, rope, caches,
                                             safe_slot, attn)
        logits = _gen._head_logits(p, out).astype(jnp.float32)   # [B, V]
        nxt = _gen._sample_slot_tokens(logits, temps, key)
        return nxt, new_caches

    def _burst_impl(self, n, arrays, caches, tokens, lens, active,
                    tables, temps, eos_arr, keys):
        """``n`` decode ticks as ONE ``lax.scan`` that never leaves the
        chip: each tick runs the SAME ``_decode_core`` as the
        single-step path (per-token sampling included, with the same
        pre-split key schedule), then latches eos in-carry — a finished
        row keeps scanning but its state freezes: length stops
        advancing, its KV writes fence off the pool via the active
        mask, and its later sampled tokens are garbage the host never
        consumes. Per-slot emit counts ride out of the scan so the host
        can place every token (and the eos finish) at its true in-scan
        step boundary. ``n`` is STATIC: one trace per pow2 burst
        length, counted by ``serve.decode_traces``."""
        import jax.numpy as jnp
        from jax import lax

        self.decode_traces += 1
        _M_DECODE_TRACES.inc(engine=self.name)

        p = {**arrays, **self._static}

        def tick(carry, key):
            tokens, lens, active, emitted, caches = carry
            nxt, caches = self._decode_core(
                caches, tokens, lens, active, tables, temps, key, p=p)
            hit = active & (eos_arr >= 0) & (nxt == eos_arr)
            carry = (jnp.where(active, nxt, tokens),
                     jnp.where(active, lens + 1, lens),
                     active & ~hit,
                     emitted + active.astype(jnp.int32),
                     caches)
            return carry, nxt

        emitted0 = jnp.zeros(self.max_slots, jnp.int32)
        (_, _, _, emitted, caches), ys = lax.scan(
            tick, (tokens, lens, active, emitted0, caches), keys,
            length=n)
        return ys, emitted, caches

    def _prefill_impl(self, arrays, caches, ids, n, table_row):
        """Prompt prefill for ONE stream: causal self-attention over
        the (bucket-padded) prompt, K/V scattered into this stream's
        pool blocks (donated — updated in place), last real token's
        logits returned. Compiles once per power-of-two length bucket
        (``serve.prefill_traces``)."""
        import jax
        import jax.numpy as jnp

        self.prefill_traces += 1
        _M_PREFILL_TRACES.inc(engine=self.name,
                              bucket=int(ids.shape[1]))

        p = {**arrays, **self._static}
        tp = ids.shape[1]
        nh, kvh, dh = self._nh, self._nkv, self._dh
        nb, bs = self.pool.num_blocks, self.block_size
        group = nh // kvh

        positions = jnp.arange(tp, dtype=jnp.int32)
        valid = positions < n                              # [Tp]
        x = jnp.take(p["embed"], ids, axis=0)[0]           # [Tp, H]
        rope = None
        if self._is_llama:
            rope = self._rope_rows(positions)
        else:
            x = x + jnp.take(p["wpe"], positions, axis=0)
        # causal within the prompt; pad rows see themselves only (their
        # K/V never reach the pool and their logits are never read)
        causal = (positions[None, :] <= positions[:, None]) \
            & valid[None, :]                               # [Tq, Tk]

        bi = jnp.clip(positions // bs, 0, self.max_blocks_per_seq - 1)
        slot = jnp.take(table_row, bi) * bs + positions % bs
        safe_slot = jnp.where(valid, slot, nb * bs)

        def attn(q, k, v, _kc, _vc):
            k_rep = jnp.repeat(k, group, axis=1) if group > 1 else k
            v_rep = jnp.repeat(v, group, axis=1) if group > 1 else v
            scores = jnp.einsum(
                "qhd,khd->hqk", q.astype(jnp.float32),
                k_rep.astype(jnp.float32)) * (dh ** -0.5)
            scores = jnp.where(causal[None], scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1)
            return jnp.einsum(
                "hqk,khd->qhd", probs,
                v_rep.astype(jnp.float32)).reshape(tp, nh * dh)

        out, new_caches = self._stack_layers(p, x, rope, caches,
                                             safe_slot, attn)
        h_last = jnp.take(out, n - 1, axis=0)              # [H]
        logits = _gen._head_logits(p, h_last[None, :])[0]
        return new_caches, logits.astype(jnp.float32)

    def _suffix_prefill_impl(self, arrays, caches, ids, n, start,
                             table_row):
        """Prefill of the UNSHARED suffix only, for a stream whose
        first ``start`` tokens were mounted from the prefix cache:
        suffix K/V scatters into this stream's own blocks at absolute
        positions ``start + i``, then each suffix row attends THROUGH
        the block table (per-row lengths ``start + i + 1``) so it sees
        the shared resident prefix it never recomputed plus the
        just-written suffix rows — scatter precedes attention per
        layer, exactly as in decode. ``start`` is jit data, so this
        compiles once per pow2 suffix bucket."""
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import paged_attention_decode

        self.prefill_traces += 1
        _M_PREFILL_TRACES.inc(engine=self.name,
                              bucket=int(ids.shape[1]))

        p = {**arrays, **self._static}
        tp = ids.shape[1]
        nh, dh = self._nh, self._dh
        nb, bs = self.pool.num_blocks, self.block_size

        offs = jnp.arange(tp, dtype=jnp.int32)
        positions = start + offs                           # absolute
        valid = offs < n
        x = jnp.take(p["embed"], ids, axis=0)[0]           # [Tp, H]
        rope = None
        if self._is_llama:
            rope = self._rope_rows(positions)
        else:
            x = x + jnp.take(p["wpe"], positions, axis=0)

        bi = jnp.clip(positions // bs, 0, self.max_blocks_per_seq - 1)
        slot = jnp.take(table_row, bi) * bs + positions % bs
        safe_slot = jnp.where(valid, slot, nb * bs)
        lengths = jnp.where(valid, positions + 1, 0)       # causal
        tables_rep = jnp.broadcast_to(
            table_row[None, :], (tp, table_row.shape[0]))

        def attn(q, _k, _v, kc, vc):
            return paged_attention_decode(
                q, kc, vc, lengths, tables_rep,
                backend=self._backend).reshape(tp, nh * dh)

        out, new_caches = self._stack_layers(p, x, rope, caches,
                                             safe_slot, attn)
        h_last = jnp.take(out, n - 1, axis=0)              # [H]
        logits = _gen._head_logits(p, h_last[None, :])[0]
        return new_caches, logits.astype(jnp.float32)

    def _cow_impl(self, caches, src, dst):
        """Copy-on-write: duplicate one physical block's K/V across
        every layer into a private block, so a stream can diverge
        inside a shared prefix block without mutating KV that other
        streams are reading. src/dst are jit data — one trace ever."""
        return [(kc.at[:, dst].set(kc[:, src]),
                 vc.at[:, dst].set(vc[:, src]))
                for kc, vc in caches]
