"""Refcounted block pool allocator for the paged KV cache.

The physical pool itself is a pair of device arrays per layer
(``[KVH, num_blocks, block_size, DH]``, the paged-attention kernel
layout); THIS object owns only the block-id bookkeeping. Since the
prefix cache landed, a physical block can be in one of three states:

- **free** — on the LIFO free-list, contents meaningless;
- **referenced** — held by one or more live streams (``refcount >= 1``;
  prefix sharing is what pushes it above 1: two streams whose prompts
  share a full-block prefix decode from the SAME physical block);
- **cached** — ``refcount == 0`` but retained because the prefix cache
  still indexes its KV contents. Cached blocks are *evictable*: they
  are reclaimed back to the free list (``reclaim``) on demand, never
  while referenced.

``alloc``/``free`` are the original PR-14 surface and remain valid:
``alloc`` hands out fresh blocks at refcount 1 and ``free`` is
``release`` without retention. Double-free detection generalizes to
refcount underflow — releasing a block more times than it is held is a
hard ``ValueError`` either way.

Exhaustion is LOUD by contract: :meth:`alloc` raises
:class:`PoolExhaustedError` instead of handing out an out-of-range id —
the silent failure mode this replaces was a clipped out-of-bounds
gather that reads another sequence's KV block (ISSUE 14 satellite; the
serving engine catches the error, evicts cached blocks, and only then
queues/preempts).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Set

__all__ = ["BlockPool", "PoolExhaustedError"]


class PoolExhaustedError(RuntimeError):
    """No free KV-cache blocks remain in the pool.

    Raised by :meth:`BlockPool.alloc`; the serving engine reacts by
    evicting prefix-cached (refcount-0) blocks, then queueing the
    admission or preempting the youngest stream; a bare
    ``generate(paged=True)`` caller fails loudly instead of gathering
    out of bounds.
    """


class BlockPool:
    """Refcounted free-list allocator over ``num_blocks`` KV blocks."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be > 0, got {num_blocks}")
        if block_size <= 0:
            raise ValueError(f"block_size must be > 0, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO: recently-freed blocks are re-issued first (their pages
        # are the likeliest to still be VMEM/cache warm on re-prefill)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}       # block id -> refcount (>= 1)
        self._cached: Set[int] = set()       # refcount-0, prefix-retained

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks retained by the prefix cache (evictable)."""
        return len(self._cached)

    @property
    def used_blocks(self) -> int:
        """Blocks referenced by live streams (cached-but-unreferenced
        blocks are reclaimable on demand, so they do not count)."""
        return self.num_blocks - len(self._free) - len(self._cached)

    @property
    def occupancy(self) -> float:
        """Fraction of the pool held by live streams (0.0 .. 1.0)."""
        return self.used_blocks / self.num_blocks

    def refcount(self, block: int) -> int:
        """Live references to ``block`` (0 for free AND cached blocks —
        ``is_cached`` distinguishes them)."""
        return self._ref.get(int(block), 0)

    def is_cached(self, block: int) -> bool:
        return int(block) in self._cached

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n: int = 1) -> List[int]:
        """Hand out ``n`` fresh block ids at refcount 1, or raise —
        atomically: either all ``n`` are granted or none are taken.
        Cached blocks are NOT tapped here; the caller decides what to
        evict (``reclaim``) before retrying."""
        if n <= 0:
            return []
        if n > len(self._free):
            raise PoolExhaustedError(
                f"KV block pool exhausted: requested {n} block(s) but "
                f"only {len(self._free)} of {self.num_blocks} are free "
                f"({self.used_blocks} in use, {len(self._cached)} "
                f"prefix-cached, block_size={self.block_size}). Evict "
                f"cached blocks, finish or preempt a stream, or size "
                f"the pool for the working set.")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def acquire(self, blocks: Iterable[int]) -> None:
        """Take an additional reference on each block (prefix sharing:
        a new stream starts decoding from resident KV). Acquiring a
        cached block revives it to refcount 1; acquiring a FREE block
        is a hard error — its contents are meaningless."""
        for b in blocks:
            b = int(b)
            if not 0 <= b < self.num_blocks:
                raise ValueError(
                    f"acquire(): block id {b} is outside the pool "
                    f"[0, {self.num_blocks})")
            if b in self._cached:
                self._cached.discard(b)
                self._ref[b] = 1
            elif b in self._ref:
                self._ref[b] += 1
            else:
                raise ValueError(
                    f"acquire(): block id {b} is free — acquiring an "
                    f"unallocated block would share garbage KV")

    def release(self, blocks: Iterable[int],
                retain: Iterable[int] = ()) -> List[int]:
        """Drop one reference per listed block (a duplicate id in one
        call drops two). Refcount underflow — releasing a block that is
        already free or cached, or more times than it is held — is a
        hard error, the generalization of PR 14's double-free check,
        and is detected BEFORE any state changes. Blocks that hit
        refcount 0 return to the free list unless listed in ``retain``
        (the prefix cache's registered blocks), which park in the
        cached state instead; the newly-cached ids are returned so the
        prefix cache can enqueue them for LRU eviction."""
        blocks = [int(b) for b in blocks]
        need: Dict[int, int] = {}
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(
                    f"release(): block id {b} is outside the pool "
                    f"[0, {self.num_blocks})")
            need[b] = need.get(b, 0) + 1
        for b, k in need.items():
            if k > self._ref.get(b, 0):
                raise ValueError(
                    f"release(): block id {b} is already free (refcount "
                    f"{self._ref.get(b, 0)}, releasing {k}) — refcount "
                    f"underflow / double free corrupts the allocator")
        retain_set = {int(b) for b in retain}
        newly_cached: List[int] = []
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if b in retain_set:
                    self._cached.add(b)
                    newly_cached.append(b)
                else:
                    self._free.append(b)
        return newly_cached

    def free(self, blocks: List[int]) -> None:
        """PR-14 surface: ``release`` with no retention."""
        self.release(blocks)

    def reclaim(self, blocks: Iterable[int]) -> None:
        """Evict cached (refcount-0) blocks back to the free list.
        Reclaiming a referenced block is a hard error — eviction must
        never pull KV out from under a live stream."""
        for b in blocks:
            b = int(b)
            if b in self._ref:
                raise ValueError(
                    f"reclaim(): block id {b} has refcount "
                    f"{self._ref[b]} — eviction only reclaims "
                    f"refcount-0 blocks")
            if b not in self._cached:
                raise ValueError(
                    f"reclaim(): block id {b} is not cached (already "
                    f"free or outside the pool)")
            self._cached.discard(b)
            self._free.append(b)
