"""Block pool allocator for the paged KV cache.

The physical pool itself is a pair of device arrays per layer
(``[KVH, num_blocks, block_size, DH]``, the paged-attention kernel
layout); THIS object owns only the block-id bookkeeping: a LIFO
free-list of physical block ids handed to sequences as their context
grows and recycled the moment a stream finishes or is preempted.

Exhaustion is LOUD by contract: :meth:`alloc` raises
:class:`PoolExhaustedError` instead of handing out an out-of-range id —
the silent failure mode this replaces was a clipped out-of-bounds
gather that reads another sequence's KV block (ISSUE 14 satellite; the
serving engine catches the error and queues/preempts instead).
"""
from __future__ import annotations

from typing import List

__all__ = ["BlockPool", "PoolExhaustedError"]


class PoolExhaustedError(RuntimeError):
    """No free KV-cache blocks remain in the pool.

    Raised by :meth:`BlockPool.alloc`; the serving engine reacts by
    queueing the admission (or preempting the youngest stream), a bare
    ``generate(paged=True)`` caller by failing loudly instead of
    gathering out of bounds.
    """


class BlockPool:
    """Free-list allocator over ``num_blocks`` physical KV blocks."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be > 0, got {num_blocks}")
        if block_size <= 0:
            raise ValueError(f"block_size must be > 0, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO: recently-freed blocks are re-issued first (their pages
        # are the likeliest to still be VMEM/cache warm on re-prefill)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def occupancy(self) -> float:
        """Fraction of the pool currently allocated (0.0 .. 1.0)."""
        return self.used_blocks / self.num_blocks

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n: int = 1) -> List[int]:
        """Hand out ``n`` physical block ids, or raise — atomically:
        either all ``n`` are granted or none are taken."""
        if n <= 0:
            return []
        if n > len(self._free):
            raise PoolExhaustedError(
                f"KV block pool exhausted: requested {n} block(s) but "
                f"only {len(self._free)} of {self.num_blocks} are free "
                f"({self.used_blocks} in use, block_size="
                f"{self.block_size}). Finish or preempt a stream, or "
                f"size the pool for the working set.")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, blocks: List[int]) -> None:
        """Return block ids to the pool (double-free is a hard error —
        including a duplicate id WITHIN one call, which would put the
        same physical block on the free list twice and hand it to two
        streams)."""
        free_set = set(self._free)
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(
                    f"free(): block id {b} is outside the pool "
                    f"[0, {self.num_blocks})")
            if b in free_set:
                raise ValueError(
                    f"free(): block id {b} is already free — double "
                    f"free corrupts the allocator")
            free_set.add(b)
        self._free.extend(blocks)
