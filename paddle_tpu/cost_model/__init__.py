"""paddle.cost_model — program cost estimation.

Reference: python/paddle/cost_model/cost_model.py (CostModel over the
static-graph cost infrastructure). TPU mapping: the analytic HBM +
roofline estimators that drive the auto-tuner and Engine.prepare.
"""
from __future__ import annotations

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        pass

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",)):
        """Analytic estimate for a transformer-shaped TuneSpace dict (the
        reference measures a program; the TPU path scores configs with
        distributed.auto_tuner's roofline model)."""
        from ..distributed.auto_tuner import (
            Candidate, TuneSpace, estimate_memory_bytes,
            estimate_step_time_s,
        )

        space = TuneSpace()
        cand = Candidate(dp=1, mp=1, pp=1, sharding_stage=0,
                         micro_batch_size=space.global_batch_size,
                         recompute=False)
        return {
            "time": estimate_step_time_s(space, cand),
            "memory": estimate_memory_bytes(space, cand),
        }

    def static_cost_data(self):
        from ..distributed import auto_tuner

        return auto_tuner.TuneSpace()
