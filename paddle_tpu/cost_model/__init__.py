"""paddle.cost_model — program cost estimation.

Reference: python/paddle/cost_model/cost_model.py (CostModel over the
static-graph cost infrastructure). TPU mapping: the analytic HBM +
roofline estimators that drive the auto-tuner and Engine.prepare.
"""
from __future__ import annotations

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        pass

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",),
                        tune_space=None, candidate=None):
        """Analytic estimate for a transformer-shaped model description
        (the reference measures a static program; the TPU path scores
        configs with distributed.auto_tuner's roofline model).

        Pass ``tune_space`` (an ``auto_tuner.TuneSpace`` or a kwargs
        dict for one) describing the model/hardware, and optionally
        ``candidate`` (an ``auto_tuner.Candidate`` or kwargs dict) for
        the parallelism config to score. Static programs are NOT
        costed on the TPU path — passing one raises instead of being
        silently ignored."""
        if startup_program is not None or main_program is not None:
            raise NotImplementedError(
                "CostModel.profile_measure on the TPU backend does not "
                "cost static programs; describe the model with "
                "tune_space=TuneSpace(...) (and optionally candidate=) "
                "instead. Refusing to silently ignore the program "
                "arguments.")
        from ..distributed.auto_tuner import (
            Candidate, TuneSpace, estimate_memory_bytes,
            estimate_step_time_s,
        )

        if tune_space is None:
            space = TuneSpace()
        elif isinstance(tune_space, TuneSpace):
            space = tune_space
        else:
            space = TuneSpace(**dict(tune_space))
        if candidate is None:
            cand = Candidate(dp=1, mp=1, pp=1, sharding_stage=0,
                             micro_batch_size=space.global_batch_size,
                             recompute=False)
        elif isinstance(candidate, Candidate):
            cand = candidate
        else:
            cand = Candidate(**dict(candidate))
        return {
            "time": estimate_step_time_s(space, cand),
            "memory": estimate_memory_bytes(space, cand),
        }

    def static_cost_data(self):
        from ..distributed import auto_tuner

        return auto_tuner.TuneSpace()
