"""ShuffleNetV2. Reference: python/paddle/vision/models/shufflenetv2.py
(channel shuffle + split units; x0_25..x2_0 and swish variant)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, reshape, split, transpose

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _conv_bn_act(inp, oup, k, s, p, groups=1, act="relu"):
    layers = [nn.Conv2D(inp, oup, k, stride=s, padding=p, groups=groups,
                        bias_attr=False), nn.BatchNorm2D(oup)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "swish":
        layers.append(nn.Swish())
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_features = oup // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn_act(inp // 2, branch_features, 1, 1, 0, act=act),
                _conv_bn_act(branch_features, branch_features, 3, 1, 1,
                             groups=branch_features, act="none"),
                _conv_bn_act(branch_features, branch_features, 1, 1, 0,
                             act=act),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn_act(inp, inp, 3, stride, 1, groups=inp, act="none"),
                _conv_bn_act(inp, branch_features, 1, 1, 0, act=act),
            )
            self.branch2 = nn.Sequential(
                _conv_bn_act(inp, branch_features, 1, 1, 0, act=act),
                _conv_bn_act(branch_features, branch_features, 3, stride, 1,
                             groups=branch_features, act="none"),
                _conv_bn_act(branch_features, branch_features, 1, 1, 0,
                             act=act),
            )

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    _stage_repeats = [4, 8, 4]
    _out_channels = {
        0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
        0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
        1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
    }

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        channels = self._out_channels[scale]
        self.conv1 = _conv_bn_act(3, channels[0], 3, 2, 1, act=act)
        self.max_pool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        inp = channels[0]
        for repeats, oup in zip(self._stage_repeats, channels[1:4]):
            stages.append(InvertedResidual(inp, oup, 2, act))
            for _ in range(repeats - 1):
                stages.append(InvertedResidual(oup, oup, 1, act))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(inp, channels[4], 1, 1, 0, act=act)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[4], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(arch, scale, act, pretrained, **kwargs):
    model = ShuffleNetV2(scale=scale, act=act, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, arch)
    return model


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x0_25", 0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x0_33", 0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x0_5", 0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x1_0", 1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x1_5", 1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_x2_0", 2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet("shufflenet_v2_swish", 1.0, "swish", pretrained, **kwargs)
