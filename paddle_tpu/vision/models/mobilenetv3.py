"""MobileNetV3 (small/large). Reference:
python/paddle/vision/models/mobilenetv3.py (SE blocks + h-swish)."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large"]


class SqueezeExcitation(nn.Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(input_channels, squeeze_channels, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_channels, input_channels, 1)
        self.hardsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        scale = self.hardsigmoid(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * scale


class InvertedResidualV3(nn.Layer):
    def __init__(self, inp, exp, out, kernel, stride, use_se, activation):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        act = nn.Hardswish if activation == "HS" else nn.ReLU
        layers = []
        if exp != inp:
            layers += [nn.Conv2D(inp, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), act()]
        layers += [
            nn.Conv2D(exp, exp, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=exp, bias_attr=False),
            nn.BatchNorm2D(exp), act(),
        ]
        if use_se:
            layers.append(SqueezeExcitation(exp, _make_divisible(exp // 4)))
        layers += [nn.Conv2D(exp, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


# (kernel, exp, out, SE, activation, stride) — reference inverted_residual_setting
_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]
_SMALL = [
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        inp = _make_divisible(16 * scale)
        layers = [nn.Conv2D(3, inp, 3, stride=2, padding=1, bias_attr=False),
                  nn.BatchNorm2D(inp), nn.Hardswish()]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(InvertedResidualV3(inp, exp_c, out_c, k, s, se, act))
            inp = out_c
        last_conv = _make_divisible(last_exp * scale)
        layers += [nn.Conv2D(inp, last_conv, 1, bias_attr=False),
                   nn.BatchNorm2D(last_conv), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            hidden = _make_divisible(1280 * scale) if last_exp == 960 else 1024
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, hidden), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(hidden, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Large(scale=scale, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, "mobilenet_v3_large")
    return model


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Small(scale=scale, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, "mobilenet_v3_small")
    return model
