"""SqueezeNet. Reference: python/paddle/vision/models/squeezenet.py
(fire modules, versions 1.0/1.1)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class MakeFire(nn.Layer):
    def __init__(self, in_channels, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self._conv = nn.Conv2D(in_channels, squeeze_channels, 1)
        self._conv_path1 = nn.Conv2D(squeeze_channels, expand1x1_channels, 1)
        self._conv_path2 = nn.Conv2D(squeeze_channels, expand3x3_channels, 3,
                                     padding=1)
        self._relu = nn.ReLU()

    def forward(self, x):
        x = self._relu(self._conv(x))
        x1 = self._relu(self._conv_path1(x))
        x2 = self._relu(self._conv_path2(x))
        return concat([x1, x2], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self._conv = nn.Conv2D(3, 96, 7, stride=2)
            fires = [(96, 16, 64, 64), (128, 16, 64, 64), (128, 32, 128, 128),
                     (256, 32, 128, 128), (256, 48, 192, 192),
                     (384, 48, 192, 192), (384, 64, 256, 256),
                     (512, 64, 256, 256)]
            self._pool_after = {0: True, 3: True, 7: True}
        elif version == "1.1":
            self._conv = nn.Conv2D(3, 64, 3, stride=2, padding=1)
            fires = [(64, 16, 64, 64), (128, 16, 64, 64), (128, 32, 128, 128),
                     (256, 32, 128, 128), (256, 48, 192, 192),
                     (384, 48, 192, 192), (384, 64, 256, 256),
                     (512, 64, 256, 256)]
            self._pool_after = {1: True, 3: True}
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        self._fires = nn.LayerList([MakeFire(*f) for f in fires])
        self._relu = nn.ReLU()
        self._max_pool = nn.MaxPool2D(3, 2)
        if num_classes > 0:
            self._drop = nn.Dropout(0.5)
            self._conv2 = nn.Conv2D(512, num_classes, 1)
        if with_pool:
            self._avg_pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self._max_pool(self._relu(self._conv(x)))
        for i, fire in enumerate(self._fires):
            x = fire(x)
            if self._pool_after.get(i):
                x = self._max_pool(x)
        if self.num_classes > 0:
            x = self._relu(self._conv2(self._drop(x)))
        if self.with_pool:
            x = self._avg_pool(x)
            x = x.flatten(1)
        return x


def _squeezenet(arch, version, pretrained, **kwargs):
    model = SqueezeNet(version, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, arch)
    return model


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("squeezenet1_0", "1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("squeezenet1_1", "1.1", pretrained, **kwargs)
