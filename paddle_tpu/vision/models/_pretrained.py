"""Pretrained-weight loading for the vision zoo.

Reference: python/paddle/vision/models/*.py, which download checkpoint
files via ``paddle.utils.download.get_weights_path_from_url``. This
build has zero network egress, so the documented stance is an OFFLINE
CACHE: ``pretrained=True`` loads ``<arch>.pdparams`` from the weights
home (``$PADDLE_TPU_WEIGHTS_HOME`` or ``~/.cache/paddle_tpu/weights``)
when present and raises an actionable error otherwise — drop the file
in place (converted with ``paddle_tpu.save(model.state_dict(), path)``)
and every ``<arch>(pretrained=True)`` constructor works.
"""
from __future__ import annotations

import os.path as osp

from ...utils.download import WEIGHTS_HOME


def load_pretrained(model, arch: str):
    """Load <arch>.pdparams from the offline weights cache into model."""
    path = osp.join(WEIGHTS_HOME, f"{arch}.pdparams")
    if not osp.exists(path):
        raise NotImplementedError(
            f"{arch}: pretrained weights are not bundled (zero-egress "
            f"build). Place a state_dict at {path} — saved with "
            "paddle_tpu.save(model.state_dict(), path) — and "
            "pretrained=True will load it.")
    from ...framework.io_ import load

    state = load(path)
    model.set_state_dict(state)
    return model
