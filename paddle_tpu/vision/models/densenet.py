"""DenseNet. Reference: python/paddle/vision/models/densenet.py
(dense blocks + transitions; 121/161/169/201/264)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_cfgs = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, num_input_features, num_output_features):
        super().__init__(
            nn.BatchNorm2D(num_input_features), nn.ReLU(),
            nn.Conv2D(num_input_features, num_output_features, 1,
                      bias_attr=False),
            nn.AvgPool2D(2, 2),
        )


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init_features, growth_rate, block_config = _cfgs[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        )
        blocks = []
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            for j in range(num_layers):
                blocks.append(_DenseLayer(num_features + j * growth_rate,
                                          growth_rate, bn_size, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = nn.Sequential(*blocks)
        self.final_norm = nn.BatchNorm2D(num_features)
        self.relu = nn.ReLU()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.relu(self.final_norm(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(arch, layers, pretrained, **kwargs):
    model = DenseNet(layers=layers, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, arch)
    return model


def densenet121(pretrained=False, **kwargs):
    return _densenet("densenet121", 121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet("densenet161", 161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet("densenet169", 169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet("densenet201", 201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet("densenet264", 264, pretrained, **kwargs)
