"""paddle.vision.transforms parity (core set).

Reference: python/paddle/vision/transforms/transforms.py (+functional.py).
Transforms accept PIL images, numpy HWC arrays, or Tensors; ToTensor
produces CHW float32 in [0,1] like the reference.
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from ...core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Pad", "Transpose",
    "to_tensor", "normalize", "resize", "center_crop", "hflip", "vflip",
]


def _to_numpy_hwc(img):
    try:
        from PIL import Image

        if isinstance(img, Image.Image):
            arr = np.asarray(img)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            return arr
    except ImportError:
        pass
    if isinstance(img, Tensor):
        img = np.asarray(img._value)
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def to_tensor(pic, data_format="CHW") -> Tensor:
    raw = _to_numpy_hwc(pic)
    arr = raw.astype(np.float32)
    if raw.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor._from_value(np.ascontiguousarray(arr))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._value)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean, std = mean.reshape(-1, 1, 1), std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor._from_value(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation="bilinear"):
    arr = _to_numpy_hwc(img)
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        if h <= w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    from PIL import Image

    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC}[interpolation]
    # PIL can't build multi-channel float images; resize per-channel in fp32
    if arr.dtype != np.uint8:
        chans = [np.asarray(Image.fromarray(arr[:, :, c].astype(np.float32),
                                            mode="F")
                            .resize((size[1], size[0]), resample))
                 for c in range(arr.shape[-1])]
        return np.stack(chans, axis=-1)
    pil = Image.fromarray(arr.squeeze(-1) if arr.shape[-1] == 1 else arr)
    out = np.asarray(pil.resize((size[1], size[0]), resample))
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def center_crop(img, output_size):
    arr = _to_numpy_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return arr[i:i + th, j:j + tw]


def hflip(img):
    return _to_numpy_hwc(img)[:, ::-1]


def vflip(img):
    return _to_numpy_hwc(img)[::-1]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


def _pad_spec(padding):
    """Paddle padding contract → np.pad spec for an HWC array.

    int p → all sides p; (lr, tb) → left/right=lr, top/bottom=tb;
    (l, t, r, b) → per-side. (reference: python/paddle/vision/transforms/
    functional_cv2.py pad semantics)
    """
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l = r = int(padding[0])
        t = b = int(padding[1])
    elif len(padding) == 4:
        l, t, r, b = (int(v) for v in padding)
    else:
        raise ValueError(f"padding must be int, 2-tuple or 4-tuple, got "
                         f"{padding!r}")
    return ((t, b), (l, r), (0, 0))


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        arr = _to_numpy_hwc(img)
        if self.padding is not None:
            arr = np.pad(arr, _pad_spec(self.padding))
        th, tw = self.size
        if self.pad_if_needed:
            h, w = arr.shape[:2]
            if h < th or w < tw:
                ph, pw = max(0, th - h), max(0, tw - w)
                arr = np.pad(arr, ((ph // 2, ph - ph // 2),
                                   (pw // 2, pw - pw // 2), (0, 0)))
        h, w = arr.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _to_numpy_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _to_numpy_hwc(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _to_numpy_hwc(img)
        spec = _pad_spec(self.padding)
        if self.padding_mode == "constant":
            return np.pad(arr, spec, constant_values=self.fill)
        mode = {"reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[self.padding_mode]
        return np.pad(arr, spec, mode=mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _to_numpy_hwc(img).transpose(self.order)

from .extra import (  # noqa: E402,F401
    crop, pad, erase, affine, rotate, perspective, to_grayscale,
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    RandomResizedCrop, BrightnessTransform, SaturationTransform,
    ContrastTransform, HueTransform, ColorJitter, RandomAffine,
    RandomRotation, RandomPerspective, Grayscale, RandomErasing,
)

__all__ += [
    "crop", "pad", "erase", "affine", "rotate", "perspective",
    "to_grayscale", "adjust_brightness", "adjust_contrast", "adjust_hue",
    "adjust_saturation", "RandomResizedCrop", "BrightnessTransform",
    "SaturationTransform", "ContrastTransform", "HueTransform", "ColorJitter",
    "RandomAffine", "RandomRotation", "RandomPerspective", "Grayscale",
    "RandomErasing", "BaseTransform",
]
