"""Vision transforms surface completion.

Reference: python/paddle/vision/transforms/transforms.py + functional.py —
color adjustments (brightness/contrast/saturation/hue, ColorJitter),
geometric warps (affine/rotate/perspective via inverse-warp bilinear
sampling), RandomResizedCrop, Grayscale, RandomErasing, crop/pad/erase
functionals. Images are numpy HWC uint8/float or paddle Tensors (CHW),
matching the package's existing convention.
"""
from __future__ import annotations

import math
import random as _random

import numpy as np

from . import _to_numpy_hwc, BaseTransform, center_crop, resize


__all__ = [
    "crop", "pad", "erase", "affine", "rotate", "perspective",
    "to_grayscale", "adjust_brightness", "adjust_contrast", "adjust_hue",
    "adjust_saturation", "RandomResizedCrop", "BrightnessTransform",
    "SaturationTransform", "ContrastTransform", "HueTransform", "ColorJitter",
    "RandomAffine", "RandomRotation", "RandomPerspective", "Grayscale",
    "RandomErasing",
]


# ---------------------------------------------------------------------------
# functional
# ---------------------------------------------------------------------------
def crop(img, top, left, height, width):
    arr = _to_numpy_hwc(img)
    return arr[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy_hwc(img)
    if isinstance(padding, int):
        l = r = t = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((t, b), (l, r), (0, 0)), mode=mode, **kw)
    return out


def erase(img, i, j, h, w, v, inplace=False):
    arr = _to_numpy_hwc(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w, :] = v
    return out


def _inverse_warp(arr, matrix, fill=0.0):
    """Sample arr (HWC) at inverse-transformed grid coords; matrix maps
    OUTPUT (x, y, 1) -> INPUT (x, y)."""
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1).reshape(-1, 3).astype(
        np.float64)
    src = coords @ np.asarray(matrix, np.float64).T  # [N, 2 or 3]
    if src.shape[1] == 3:
        src = src[:, :2] / np.maximum(src[:, 2:3], 1e-9)
    sx = src[:, 0].reshape(h, w)
    sy = src[:, 1].reshape(h, w)
    x0 = np.floor(sx).astype(int)
    y0 = np.floor(sy).astype(int)
    wx = sx - x0
    wy = sy - y0

    def sample(yy, xx):
        ok = (xx >= 0) & (xx < w) & (yy >= 0) & (yy < h)
        yc = np.clip(yy, 0, h - 1)
        xc = np.clip(xx, 0, w - 1)
        vals = arr[yc, xc].astype(np.float64)
        vals[~ok] = fill
        return vals

    out = (sample(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
           + sample(y0, x0 + 1) * (wx * (1 - wy))[..., None]
           + sample(y0 + 1, x0) * ((1 - wx) * wy)[..., None]
           + sample(y0 + 1, x0 + 1) * (wx * wy)[..., None])
    return out.astype(arr.dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    cx, cy = center
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    # torch/paddle convention: M = T(center) R(angle) Shear Scale T(-center) T(translate)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0]]) * scale
    m[0, 2] = cx + translate[0] - (m[0, 0] * cx + m[0, 1] * cy)
    m[1, 2] = cy + translate[1] - (m[1, 0] * cx + m[1, 1] * cy)
    # invert for inverse warping
    full = np.vstack([m, [0, 0, 1]])
    return np.linalg.inv(full)[:2]


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    arr = _to_numpy_hwc(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if np.isscalar(shear):
        shear = (shear, 0.0)
    inv = _affine_matrix(angle, translate, scale, shear, center)
    return _inverse_warp(arr, inv, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _to_numpy_hwc(img)
    h, w = arr.shape[:2]
    if expand:
        rad = math.radians(angle)
        nw = int(abs(w * math.cos(rad)) + abs(h * math.sin(rad)) + 0.5)
        nh = int(abs(w * math.sin(rad)) + abs(h * math.cos(rad)) + 0.5)
        pad_l = (nw - w) // 2
        pad_t = (nh - h) // 2
        # expansion border must carry the requested fill (per-channel),
        # not zeros — out-of-bounds warp taps sample this canvas
        canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[...] = np.asarray(fill, dtype=arr.dtype).reshape(1, 1, -1)
        canvas[pad_t:pad_t + h, pad_l:pad_l + w] = arr
        arr = canvas
        h, w = nh, nw
        center = None
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)
    return _inverse_warp(arr, inv, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Warp mapping startpoints -> endpoints (reference functional
    perspective; solves the 8-dof homography)."""
    arr = _to_numpy_hwc(img)
    a = []
    bvec = []
    # solve homography endpoints -> startpoints (inverse warp)
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec.extend([sx, sy])
    coeffs = np.linalg.lstsq(np.asarray(a, np.float64),
                             np.asarray(bvec, np.float64), rcond=None)[0]
    hmat = np.append(coeffs, 1.0).reshape(3, 3)
    return _inverse_warp(arr, hmat, fill)


_GRAY_W = np.array([0.299, 0.587, 0.114])


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy_hwc(img)
    gray = (arr.astype(np.float64) @ _GRAY_W)[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return gray.astype(arr.dtype)


def _blend(a, b, factor, dtype):
    out = a.astype(np.float64) * factor + b.astype(np.float64) * (1 - factor)
    if np.issubdtype(dtype, np.integer):
        out = np.clip(out, 0, 255)
    return out.astype(dtype)


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy_hwc(img)
    return _blend(arr, np.zeros_like(arr), brightness_factor, arr.dtype)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy_hwc(img)
    mean = (arr.astype(np.float64) @ _GRAY_W).mean()
    return _blend(arr, np.full_like(arr, mean), contrast_factor, arr.dtype)


def adjust_saturation(img, saturation_factor):
    arr = _to_numpy_hwc(img)
    gray = (arr.astype(np.float64) @ _GRAY_W)[..., None]
    return _blend(arr, np.broadcast_to(gray, arr.shape),
                  saturation_factor, arr.dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV roundtrip
    (reference functional adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _to_numpy_hwc(img)
    dtype = arr.dtype
    x = arr.astype(np.float64)
    if np.issubdtype(dtype, np.integer):
        x = x / 255.0
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = x.max(-1)
    minc = x.min(-1)
    v = maxc
    diff = maxc - minc
    s = np.where(maxc > 0, diff / np.maximum(maxc, 1e-12), 0.0)
    diff_safe = np.where(diff == 0, 1.0, diff)
    rc = (maxc - r) / diff_safe
    gc = (maxc - g) / diff_safe
    bc = (maxc - b) / diff_safe
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(diff == 0, 0.0, h / 6.0 % 1.0)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if np.issubdtype(dtype, np.integer):
        out = np.clip(out * 255.0, 0, 255)
    return out.astype(dtype)


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------
class RandomResizedCrop(BaseTransform):
    """Reference: transforms.py RandomResizedCrop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_numpy_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _random.uniform(*self.scale) * area
            log_ratio = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            aspect = math.exp(_random.uniform(*log_ratio))
            cw = int(round(math.sqrt(target_area * aspect)))
            ch = int(round(math.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = _random.randint(0, h - ch)
                left = _random.randint(0, w - cw)
                cropped = arr[top:top + ch, left:left + cw]
                return resize(cropped, self.size,
                              self.interpolation)
        return resize(center_crop(arr, min(h, w)),
                      self.size, self.interpolation)


class _FactorTransform(BaseTransform):
    FN = None

    def __init__(self, value, keys=None):
        v = float(value)
        if v < 0:
            raise ValueError("value must be non-negative")
        self.value = [max(0.0, 1 - v), 1 + v]

    def _apply_image(self, img):
        factor = _random.uniform(*self.value)
        return type(self).FN(img, factor)


class BrightnessTransform(_FactorTransform):
    FN = staticmethod(adjust_brightness)


class ContrastTransform(_FactorTransform):
    FN = staticmethod(adjust_contrast)


class SaturationTransform(_FactorTransform):
    FN = staticmethod(adjust_saturation)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        v = float(value)
        if not 0 <= v <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = [-v, v]

    def _apply_image(self, img):
        return adjust_hue(img, _random.uniform(*self.value))


class ColorJitter(BaseTransform):
    """Reference: transforms.py ColorJitter — random order of the four
    adjustments."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(self.transforms)
        _random.shuffle(order)
        for t in order:
            img = t._apply_image(img)
        return img


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = ((-degrees, degrees) if np.isscalar(degrees)
                        else tuple(degrees))
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_numpy_hwc(img)
        h, w = arr.shape[:2]
        angle = _random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = _random.uniform(-self.translate[0], self.translate[0]) * w
            ty = _random.uniform(-self.translate[1], self.translate[1]) * h
        sc = (_random.uniform(*self.scale) if self.scale is not None else 1.0)
        sh = (0.0, 0.0)
        if self.shear is not None:
            shear = self.shear
            if np.isscalar(shear):
                sh = (_random.uniform(-shear, shear), 0.0)
            elif len(shear) == 2:
                sh = (_random.uniform(shear[0], shear[1]), 0.0)
            else:
                sh = (_random.uniform(shear[0], shear[1]),
                      _random.uniform(shear[2], shear[3]))
        return affine(img, angle, (tx, ty), sc, sh, self.interpolation,
                      self.fill, self.center)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        self.degrees = ((-degrees, degrees) if np.isscalar(degrees)
                        else tuple(degrees))
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        return rotate(img, _random.uniform(*self.degrees),
                      self.interpolation, self.expand, self.center,
                      self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if _random.random() >= self.prob:
            return img
        arr = _to_numpy_hwc(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        hd = int(h * d / 2)
        wd = int(w * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [
            (_random.randint(0, wd), _random.randint(0, hd)),
            (w - 1 - _random.randint(0, wd), _random.randint(0, hd)),
            (w - 1 - _random.randint(0, wd), h - 1 - _random.randint(0, hd)),
            (_random.randint(0, wd), h - 1 - _random.randint(0, hd)),
        ]
        return perspective(img, start, end, self.interpolation, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    """Reference: transforms.py RandomErasing (Zhong et al. 2020)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if _random.random() >= self.prob:
            return img
        arr = _to_numpy_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = _random.uniform(*self.scale) * area
            aspect = math.exp(_random.uniform(math.log(self.ratio[0]),
                                              math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target / aspect)))
            ew = int(round(math.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = _random.randint(0, h - eh)
                left = _random.randint(0, w - ew)
                v = (np.random.randn(eh, ew, arr.shape[2])
                     if self.value == "random" else self.value)
                return erase(arr, top, left, eh, ew, v)
        return img
