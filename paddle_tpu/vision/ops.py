"""Detection / vision operators — ``paddle.vision.ops`` parity.

Reference: python/paddle/vision/ops.py (yolo_box :275, prior_box :436,
box_coder :582, deform_conv2d :764, roi_pool :1571, roi_align :1704,
nms :1933, psroi_pool :1440, distribute_fpn_proposals :1172).

Device ops (roi_align/roi_pool/psroi_pool/deform_conv2d/yolo_box/box_coder/
prior_box) are single fused XLA programs built from gathers and einsums —
differentiable where the reference's are. Selection ops with
data-dependent output sizes (nms, distribute_fpn_proposals) run host-side
in numpy, matching the reference's CPU kernels in role."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..ops._helpers import defprim, ensure_tensor

__all__ = [
    "yolo_box", "prior_box", "box_coder", "deform_conv2d", "roi_pool",
    "roi_align", "psroi_pool", "nms", "distribute_fpn_proposals",
    "read_file", "decode_jpeg",
]


# --------------------------------------------------------------------------
# RoI ops
# --------------------------------------------------------------------------
def _bilinear(img, y, x):
    """Bilinear sample img (C,H,W) at float coords y,x (...,) → (C, ...)."""
    h, w = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def at(yy, xx):
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return img[:, yy, xx]

    valid = (y > -1.0) & (y < h) & (x > -1.0) & (x < w)
    out = (
        at(y0, x0) * (wy0 * wx0)
        + at(y0, x1) * (wy0 * wx1)
        + at(y1, x0) * (wy1 * wx0)
        + at(y1, x1) * (wy1 * wx1)
    )
    return jnp.where(valid, out, 0.0)


def _bilinear_zero_pad(img, y, x):
    """Bilinear sample where each out-of-bounds TAP contributes zero (the
    reference deformable-conv im2col convention) — unlike _bilinear's
    RoIAlign-style edge clamping."""
    h, w = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def at(yy, xx):
        inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return img[:, yc, xc] * inb

    return (
        at(y0, x0) * (wy0 * wx0)
        + at(y0, x1) * (wy0 * wx1)
        + at(y1, x0) * (wy1 * wx0)
        + at(y1, x1) * (wy1 * wx1)
    )


def _roi_align_fwd(x, boxes, box_img_idx, *, output_size, spatial_scale,
                   sampling_ratio, aligned):
    ph, pw = output_size

    def one_roi(box, img_idx):
        img = x[img_idx]
        offset = 0.5 if aligned else 0.0
        x1 = box[0] * spatial_scale - offset
        y1 = box[1] * spatial_scale - offset
        x2 = box[2] * spatial_scale - offset
        y2 = box[3] * spatial_scale - offset
        rh = y2 - y1
        rw = x2 - x1
        if not aligned:
            rh = jnp.maximum(rh, 1.0)
            rw = jnp.maximum(rw, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        n = sampling_ratio if sampling_ratio > 0 else 2
        iy = (jnp.arange(n) + 0.5) / n
        # sample grid: (ph, n) y-coords per bin row, likewise x
        ys = y1 + (jnp.arange(ph)[:, None] + iy[None, :]) * bin_h  # (ph, n)
        xs = x1 + (jnp.arange(pw)[:, None] + iy[None, :]) * bin_w  # (pw, n)
        yy = ys.reshape(-1)[:, None]            # (ph*n, 1)
        xx = xs.reshape(-1)[None, :]            # (1, pw*n)
        samples = _bilinear(img, jnp.broadcast_to(yy, (ph * n, pw * n)),
                            jnp.broadcast_to(xx, (ph * n, pw * n)))
        c = samples.shape[0]
        samples = samples.reshape(c, ph, n, pw, n)
        return samples.mean(axis=(2, 4))        # (C, ph, pw)

    return jax.vmap(one_roi)(boxes, box_img_idx)


defprim("roi_align_p", _roi_align_fwd)


def _box_image_index(boxes_num):
    counts = np.asarray(boxes_num, "int64")
    return np.repeat(np.arange(len(counts)), counts).astype("int32")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference ops.py:1704). XLA requires a static sample grid,
    so sampling_ratio<=0 uses a FIXED 2x2 grid per bin rather than the
    reference's per-RoI adaptive ceil(roi_size/pooled_size) — pass an
    explicit sampling_ratio (detection configs typically use 2) for exact
    parity with a given reference setting."""
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    idx = Tensor._from_value(
        jnp.asarray(_box_image_index(ensure_tensor(boxes_num)._value))
    )
    return apply("roi_align_p", x, boxes, idx,
                 output_size=tuple(int(s) for s in output_size),
                 spatial_scale=float(spatial_scale),
                 sampling_ratio=int(sampling_ratio), aligned=bool(aligned))


def _roi_pool_fwd(x, boxes, box_img_idx, *, output_size, spatial_scale):
    ph, pw = output_size
    h, w = x.shape[-2], x.shape[-1]

    def one_roi(box, img_idx):
        img = x[img_idx]
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        ys = jnp.arange(h)[None, :]             # (1, H)
        xs = jnp.arange(w)[None, :]             # (1, W)
        # bin membership masks per output cell
        bins_y0 = y1 + jnp.floor(jnp.arange(ph) * rh / ph)
        bins_y1 = y1 + jnp.ceil((jnp.arange(ph) + 1) * rh / ph)
        bins_x0 = x1 + jnp.floor(jnp.arange(pw) * rw / pw)
        bins_x1 = x1 + jnp.ceil((jnp.arange(pw) + 1) * rw / pw)
        my = (ys >= bins_y0[:, None]) & (ys < bins_y1[:, None])  # (ph, H)
        mx = (xs >= bins_x0[:, None]) & (xs < bins_x1[:, None])  # (pw, W)
        mask = my[:, None, :, None] & mx[None, :, None, :]       # (ph,pw,H,W)
        vals = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = vals.max(axis=(-2, -1))                            # (C, ph, pw)
        return jnp.where(jnp.isinf(out), 0.0, out)

    return jax.vmap(one_roi)(boxes, box_img_idx)


defprim("roi_pool_p", _roi_pool_fwd)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    idx = Tensor._from_value(
        jnp.asarray(_box_image_index(ensure_tensor(boxes_num)._value))
    )
    return apply("roi_pool_p", x, boxes, idx,
                 output_size=tuple(int(s) for s in output_size),
                 spatial_scale=float(spatial_scale))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference ops.py:1440): input channels
    C = output_channels*ph*pw; output cell (i,j) averages its own channel
    group within the bin."""
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = (int(s) for s in output_size)
    if x.shape[1] % (ph * pw):
        raise ValueError(
            f"input channel ({x.shape[1]}) must be divisible by "
            f"output_size^2 ({ph * pw})"
        )
    idx = Tensor._from_value(
        jnp.asarray(_box_image_index(ensure_tensor(boxes_num)._value))
    )
    return apply("psroi_pool_p", x, boxes, idx, output_size=(ph, pw),
                 spatial_scale=float(spatial_scale))


def _psroi_pool_fwd(x, boxes, box_img_idx, *, output_size, spatial_scale):
    ph, pw = output_size
    out_c = x.shape[1] // (ph * pw)
    h, w = x.shape[-2], x.shape[-1]

    def one_roi(box, img_idx):
        img = x[img_idx].reshape(out_c, ph, pw, h, w)
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        rh = jnp.maximum(box[3] * spatial_scale - y1, 0.1)
        rw = jnp.maximum(box[2] * spatial_scale - x1, 0.1)
        ys = jnp.arange(h)[None, :]
        xs = jnp.arange(w)[None, :]
        bins_y0 = jnp.floor(y1 + jnp.arange(ph) * rh / ph)
        bins_y1 = jnp.ceil(y1 + (jnp.arange(ph) + 1) * rh / ph)
        bins_x0 = jnp.floor(x1 + jnp.arange(pw) * rw / pw)
        bins_x1 = jnp.ceil(x1 + (jnp.arange(pw) + 1) * rw / pw)
        my = (ys >= bins_y0[:, None]) & (ys < bins_y1[:, None])
        mx = (xs >= bins_x0[:, None]) & (xs < bins_x1[:, None])
        mask = (my[:, None, :, None] & mx[None, :, None, :]).astype(img.dtype)
        # img[c, i, j, :, :] averaged over its (i, j) bin
        s = jnp.einsum("cijhw,ijhw->cij", img, mask)
        cnt = jnp.maximum(mask.sum(axis=(-2, -1)), 1.0)
        return s / cnt

    return jax.vmap(one_roi)(boxes, box_img_idx)


defprim("psroi_pool_p", _psroi_pool_fwd)


# --------------------------------------------------------------------------
# box ops
# --------------------------------------------------------------------------
def _box_coder_fwd(prior_box, prior_var, target_box, *, code_type, normalized,
                   axis):
    if code_type == "encode_center_size":
        pw = prior_box[:, 2] - prior_box[:, 0] + (0 if normalized else 1)
        ph_ = prior_box[:, 3] - prior_box[:, 1] + (0 if normalized else 1)
        px = prior_box[:, 0] + pw * 0.5
        py = prior_box[:, 1] + ph_ * 0.5
        tw = target_box[:, 2] - target_box[:, 0] + (0 if normalized else 1)
        th = target_box[:, 3] - target_box[:, 1] + (0 if normalized else 1)
        tx = target_box[:, 0] + tw * 0.5
        ty = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tx[:, None] - px[None, :]) / pw[None, :],
            (ty[:, None] - py[None, :]) / ph_[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph_[None, :]),
        ], axis=-1)                              # (T, P, 4)
        return out / prior_var[None, :, :]
    # decode_center_size: target (N, P, 4) deltas around priors
    pb = jnp.expand_dims(prior_box, axis)        # broadcast along axis
    pv = jnp.expand_dims(prior_var, axis)
    pw = pb[..., 2] - pb[..., 0] + (0 if normalized else 1)
    ph_ = pb[..., 3] - pb[..., 1] + (0 if normalized else 1)
    px = pb[..., 0] + pw * 0.5
    py = pb[..., 1] + ph_ * 0.5
    d = target_box * pv
    ox = d[..., 0] * pw + px
    oy = d[..., 1] * ph_ + py
    ow = jnp.exp(d[..., 2]) * pw
    oh = jnp.exp(d[..., 3]) * ph_
    sub = 0 if normalized else 1
    return jnp.stack([
        ox - ow * 0.5, oy - oh * 0.5,
        ox + ow * 0.5 - sub, oy + oh * 0.5 - sub,
    ], axis=-1)


defprim("box_coder_p", _box_coder_fwd)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(f"unknown code_type: {code_type}")
    prior_box = ensure_tensor(prior_box)
    target_box = ensure_tensor(target_box)
    if isinstance(prior_box_var, (list, tuple)):
        pv = Tensor._from_value(
            jnp.broadcast_to(jnp.asarray(prior_box_var, jnp.float32),
                             tuple(prior_box.shape))
        )
    else:
        pv = ensure_tensor(prior_box_var)
    return apply("box_coder_p", prior_box, pv, target_box,
                 code_type=code_type, normalized=bool(box_normalized),
                 axis=int(axis))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference ops.py:436) — host-built constant tables
    (depend only on shapes/config), returned as device tensors."""
    input, image = ensure_tensor(input), ensure_tensor(image)
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        ar_whs = [
            (ms * math.sqrt(ar), ms / math.sqrt(ar))
            for ar in ars if abs(ar - 1.0) >= 1e-6
        ]
        big = (
            [(math.sqrt(ms * float(max_sizes[k])),) * 2] if max_sizes else []
        )
        if min_max_aspect_ratios_order:
            whs += [(ms, ms)] + big + ar_whs     # min, max, ARs (reference)
        else:
            whs += [(ms, ms)] + ar_whs + big     # min, ARs, max (reference)

    cy = ((np.arange(fh, dtype="float32") + offset) * step_h)[:, None, None]
    cx = ((np.arange(fw, dtype="float32") + offset) * step_w)[None, :, None]
    wh = np.asarray(whs, "float32")                      # (K, 2)
    bw = wh[None, None, :, 0] / 2
    bh = wh[None, None, :, 1] / 2
    boxes = np.stack(np.broadcast_arrays(
        (cx - bw) / iw, (cy - bh) / ih, (cx + bw) / iw, (cy + bh) / ih,
    ), axis=-1).astype("float32")                        # (fh, fw, K, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(
        np.asarray(variance, "float32"), boxes.shape
    ).copy()
    return Tensor._from_value(jnp.asarray(boxes)), Tensor._from_value(
        jnp.asarray(var)
    )


def _yolo_box_fwd(x, img_size, *, anchors, class_num, conf_thresh,
                  downsample_ratio, clip_bbox, scale_x_y, iou_aware,
                  iou_aware_factor):
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    if iou_aware:
        # layout (reference yolo_box op): first na channels are per-anchor
        # IoU predictions, then the standard na*(5+cls) block
        ioup = jax.nn.sigmoid(x[:, :na])            # (n, na, h, w)
        x = x[:, na:]
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[:, None]
    sx, sy = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * sx + sy + gx) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * sx + sy + gy) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (downsample_ratio * w)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (downsample_ratio * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    if iou_aware:
        conf = conf ** (1.0 - iou_aware_factor) * ioup ** iou_aware_factor
    keep = conf >= conf_thresh
    conf = jnp.where(keep, conf, 0.0)
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(jnp.float32)[:, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None]
    x1 = (bx - bw / 2).reshape(n, -1) * imw
    y1 = (by - bh / 2).reshape(n, -1) * imh
    x2 = (bx + bw / 2).reshape(n, -1) * imw
    y2 = (by + bh / 2).reshape(n, -1) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    # reference zeroes suppressed predictions' boxes too, not just scores
    boxes = boxes * keep.reshape(n, -1)[..., None]
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return boxes, scores


defprim("yolo_box_p", _yolo_box_fwd, multi_out=True)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    x, img_size = ensure_tensor(x), ensure_tensor(img_size)
    return apply("yolo_box_p", x, img_size, anchors=tuple(int(a) for a in anchors),
                 class_num=int(class_num), conf_thresh=float(conf_thresh),
                 downsample_ratio=int(downsample_ratio),
                 clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y),
                 iou_aware=bool(iou_aware),
                 iou_aware_factor=float(iou_aware_factor))


# --------------------------------------------------------------------------
# deformable convolution
# --------------------------------------------------------------------------
def _deform_conv2d_fwd(x, offset, weight, mask, *, stride, padding, dilation,
                       deformable_groups, groups, use_mask):
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = stride
    ph_, pw_ = padding
    dh, dw = dilation
    out_h = (h + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (w + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))

    base_y = (jnp.arange(out_h) * sh)[:, None, None]            # (oh,1,1)
    base_x = (jnp.arange(out_w) * sw)[None, :, None]            # (1,ow,1)
    k_y = jnp.repeat(jnp.arange(kh) * dh, kw)                   # (kh*kw,)
    k_x = jnp.tile(jnp.arange(kw) * dw, kh)                     # (kh*kw,)

    off = offset.reshape(n, deformable_groups, kh * kw, 2, out_h, out_w)
    m = (mask.reshape(n, deformable_groups, kh * kw, out_h, out_w)
         if use_mask else None)
    ch_per_dg = cin // deformable_groups

    def one_image(img, off_i, m_i):
        def one_dg(dg):
            ys = base_y + k_y[None, None, :] + off_i[dg, :, 0].transpose(1, 2, 0)
            xs = base_x + k_x[None, None, :] + off_i[dg, :, 1].transpose(1, 2, 0)
            chans = jax.lax.dynamic_slice_in_dim(img, dg * ch_per_dg, ch_per_dg, 0)
            samp = _bilinear_zero_pad(chans, ys, xs)  # (ch, oh, ow, kk)
            if m_i is not None:
                samp = samp * m_i[dg].transpose(1, 2, 0)[None]
            return samp

        return jnp.concatenate(
            [one_dg(dg) for dg in range(deformable_groups)], axis=0
        )  # (cin, oh, ow, kk)

    cols = jax.vmap(lambda im, of: one_image(
        im, of, None))(xp, off) if m is None else jax.vmap(one_image)(xp, off, m)
    # (n, cin, oh, ow, kh*kw) x weight (cout, cin/g, kh*kw) with groups
    wflat = weight.reshape(groups, cout // groups, cin_g, kh * kw)
    cols = cols.reshape(n, groups, cin // groups, out_h, out_w, kh * kw)
    return jnp.einsum("ngchwk,gock->ngohw", cols, wflat).reshape(
        n, cout, out_h, out_w
    )


defprim("deform_conv2d_p", _deform_conv2d_fwd)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    x, offset, weight = ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)
    use_mask = mask is not None
    mask_t = ensure_tensor(mask) if use_mask else Tensor._from_value(
        jnp.zeros((1,), jnp.float32)
    )
    out = apply("deform_conv2d_p", x, offset, weight, mask_t,
                stride=pair(stride), padding=pair(padding),
                dilation=pair(dilation),
                deformable_groups=int(deformable_groups), groups=int(groups),
                use_mask=use_mask)
    if bias is not None:
        out = out + ensure_tensor(bias).reshape([1, -1, 1, 1])
    return out


# --------------------------------------------------------------------------
# selection ops (host-side: data-dependent output sizes)
# --------------------------------------------------------------------------
def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference ops.py:1933): returns kept indices, score-sorted;
    with category_idxs the suppression is per-category."""
    b = np.asarray(ensure_tensor(boxes)._value, "float64")
    n = b.shape[0]
    s = (np.asarray(ensure_tensor(scores)._value, "float64")
         if scores is not None else None)

    iou = _iou_matrix(b)

    def hard_nms(idxs):
        order = idxs if s is None else idxs[np.argsort(-s[idxs], kind="stable")]
        keep = []
        suppressed = np.zeros(n, bool)
        for i in order:
            if suppressed[i]:
                continue
            keep.append(i)
            suppressed |= iou[i] > iou_threshold
            suppressed[i] = True
        return keep

    if category_idxs is None:
        keep = hard_nms(np.arange(n))
    else:
        cats = np.asarray(ensure_tensor(category_idxs)._value)
        if categories is None:
            raise ValueError("categories is required when category_idxs is given")
        keep = []
        for c in categories:
            keep.extend(hard_nms(np.nonzero(cats == c)[0]))
        if s is not None:
            keep = sorted(keep, key=lambda i: -s[i])
    if top_k is not None:
        keep = keep[: int(top_k)]
    return Tensor._from_value(jnp.asarray(np.asarray(keep, "int64")))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (reference ops.py:1172). Returns
    (rois per level, restore index, rois_num per level when given)."""
    rois = np.asarray(ensure_tensor(fpn_rois)._value, "float64")
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(
        np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
        * np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    )
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype("int64")
    num_levels = max_level - min_level + 1
    outs, order = [], []
    for lv in range(min_level, min_level + num_levels):
        idx = np.nonzero(level == lv)[0]
        order.append(idx)
        outs.append(Tensor._from_value(jnp.asarray(rois[idx].astype("float32"))))
    order_cat = np.concatenate(order) if order else np.zeros(0, "int64")
    restore = np.empty_like(order_cat)
    restore[order_cat] = np.arange(len(order_cat))
    restore_t = Tensor._from_value(jnp.asarray(restore.astype("int32")[:, None]))
    if rois_num is not None:
        counts = np.asarray(ensure_tensor(rois_num)._value, "int64")
        img_of_roi = np.repeat(np.arange(len(counts)), counts)
        per_level_counts = [
            Tensor._from_value(jnp.asarray(np.bincount(
                img_of_roi[level == lv], minlength=len(counts)
            ).astype("int32")))
            for lv in range(min_level, min_level + num_levels)
        ]
        return outs, restore_t, per_level_counts
    return outs, restore_t, None


# --------------------------------------------------------------------------
# image IO
# --------------------------------------------------------------------------
def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor._from_value(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode via PIL (the reference uses nvjpeg on GPU; decode is a
    host-side input-pipeline op on TPU)."""
    import io

    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError("decode_jpeg requires Pillow") from e

    raw = bytes(np.asarray(ensure_tensor(x)._value, np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor._from_value(jnp.asarray(arr))


# ---------------------------------------------------------------------------
# Layer wrappers + remaining detection ops
# (reference: python/paddle/vision/ops.py RoIPool/RoIAlign/PSRoIPool/
#  DeformConv2D classes, yolo_loss, matrix_nms, generate_proposals)
# ---------------------------------------------------------------------------
from ..nn.layer import Layer


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class DeformConv2D(Layer):
    """Reference: vision/ops.py DeformConv2D — owns the conv weight/bias;
    offsets (and masks, v2) come from the caller."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size if isinstance(kernel_size, (list, tuple))
              else (kernel_size, kernel_size))
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — decayed rescoring instead of hard suppression.

    Reference: vision/ops.py matrix_nms; bboxes [N, M, 4],
    scores [N, C, M]. Returns concatenated [label, score, x1, y1, x2, y2]
    rows per image.
    """
    import numpy as np

    b_np = np.asarray(ensure_tensor(bboxes)._value)
    s_np = np.asarray(ensure_tensor(scores)._value)
    n, c, m = s_np.shape
    all_rows, all_idx, rois_num = [], [], []
    for i in range(n):
        rows = []
        for cls in range(c):
            if cls == background_label:
                continue
            sc = s_np[i, cls]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes_c = b_np[i, order]
            sc_c = sc[order]
            ious = np.asarray(_iou_matrix(jnp.asarray(boxes_c)))
            ious = np.triu(ious, 1)          # ious[i, j], i higher-scored
            # compensation: each suppressor i is discounted by ITS OWN max
            # overlap with boxes scored above it (SOLOv2 matrix_nms) —
            # broadcast per ROW, not per column
            ious_cmax = ious.max(axis=0)
            if use_gaussian:
                decay = np.exp(-(ious ** 2 - ious_cmax[:, None] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - ious) / np.maximum(1 - ious_cmax[:, None],
                                                 1e-9)).min(axis=0)
            new_sc = sc_c * decay
            ok = new_sc >= post_threshold
            for j in np.where(ok)[0]:
                rows.append(([cls, new_sc[j], *boxes_c[j]], order[j]))
        # sort rows and their source indices together
        rows.sort(key=lambda r: -r[0][1])
        rows = rows[:keep_top_k]
        rois_num.append(len(rows))
        all_rows.extend(r for r, _ in rows)
        all_idx.extend(j for _, j in rows)
    out = Tensor._from_value(jnp.asarray(
        np.asarray(all_rows, dtype=np.float32).reshape(-1, 6)))
    outs = [out]
    if return_index:
        outs.append(Tensor._from_value(jnp.asarray(
            np.asarray(all_idx, dtype=np.int32))))
    if return_rois_num:
        outs.append(Tensor._from_value(jnp.asarray(
            np.asarray(rois_num, dtype=np.int32))))
    return tuple(outs) if len(outs) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference: vision/ops.py generate_proposals):
    decode anchor deltas, clip to image, filter small, NMS."""
    import numpy as np

    s = np.asarray(ensure_tensor(scores)._value)        # [N, A, H, W]
    d = np.asarray(ensure_tensor(bbox_deltas)._value)   # [N, 4A, H, W]
    im = np.asarray(ensure_tensor(img_size)._value)     # [N, 2]
    anc = np.asarray(ensure_tensor(anchors)._value).reshape(-1, 4)
    var = np.asarray(ensure_tensor(variances)._value).reshape(-1, 4)
    n = s.shape[0]
    rois, roi_probs, rois_num = [], [], []
    for i in range(n):
        sc = s[i].transpose(1, 2, 0).reshape(-1)
        dl = d[i].reshape(-1, 4, s.shape[2], s.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc)[:pre_nms_top_n]
        sc_k, dl_k, anc_k, var_k = sc[order], dl[order], anc[order], var[order]
        # decode (variance-scaled xywh deltas)
        aw = anc_k[:, 2] - anc_k[:, 0]
        ah = anc_k[:, 3] - anc_k[:, 1]
        acx = anc_k[:, 0] + aw / 2
        acy = anc_k[:, 1] + ah / 2
        cx = var_k[:, 0] * dl_k[:, 0] * aw + acx
        cy = var_k[:, 1] * dl_k[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(var_k[:, 2] * dl_k[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(var_k[:, 3] * dl_k[:, 3], 10.0))
        boxes = np.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2,
                          cy + bh / 2], axis=1)
        h_im, w_im = im[i]
        # pixel_offset toggles the clip bound and the +1 size convention
        # (reference generate_proposals kernel)
        off = 1.0 if pixel_offset else 0.0
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, w_im - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, h_im - off)
        ok = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
              & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, sc_k = boxes[ok], sc_k[ok]
        keep = np.asarray(nms(Tensor._from_value(jnp.asarray(
            boxes.astype(np.float32))), nms_thresh,
            Tensor._from_value(jnp.asarray(sc_k.astype(np.float32)))
        )._value)[:post_nms_top_n]
        rois.append(boxes[keep])
        roi_probs.append(sc_k[keep])
        rois_num.append(len(keep))
    rois_t = Tensor._from_value(jnp.asarray(
        np.concatenate(rois, 0).astype(np.float32)))
    probs_t = Tensor._from_value(jnp.asarray(
        np.concatenate(roi_probs, 0).astype(np.float32)))
    if return_rois_num:
        return rois_t, probs_t, Tensor._from_value(
            jnp.asarray(np.asarray(rois_num, np.int32)))
    return rois_t, probs_t


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: vision/ops.py yolo_loss — phi yolo_loss
    kernel): per-cell objectness + box regression + classification over
    assigned anchors."""
    xv = ensure_tensor(x)
    gt_box = ensure_tensor(gt_box)
    gt_label = ensure_tensor(gt_label)
    use_score = gt_score is not None
    gs = ensure_tensor(gt_score) if use_score else gt_box
    return apply("yolo_loss_p", xv, gt_box, gt_label, gs,
                 anchors=tuple(anchors), anchor_mask=tuple(anchor_mask),
                 class_num=int(class_num), ignore_thresh=float(ignore_thresh),
                 downsample_ratio=int(downsample_ratio),
                 use_label_smooth=bool(use_label_smooth),
                 scale_x_y=float(scale_x_y), use_score=use_score)


def _yolo_loss_fwd(x, gt_box, gt_label, gt_score, *, anchors, anchor_mask,
                   class_num, ignore_thresh, downsample_ratio,
                   use_label_smooth, scale_x_y, use_score):
    n, c, h, w = x.shape
    an_num = len(anchor_mask)
    x = x.reshape(n, an_num, 5 + class_num, h, w).astype(jnp.float32)
    # scale_x_y widens the sigmoid range: s*sig(x) - (s-1)/2
    px = scale_x_y * jax.nn.sigmoid(x[:, :, 0]) - 0.5 * (scale_x_y - 1.0)
    py = scale_x_y * jax.nn.sigmoid(x[:, :, 1]) - 0.5 * (scale_x_y - 1.0)
    pw_raw = x[:, :, 2]
    ph_raw = x[:, :, 3]
    obj_logit = x[:, :, 4]
    cls_logit = x[:, :, 5:]
    input_size = downsample_ratio * h
    masked = [(anchors[2 * m], anchors[2 * m + 1]) for m in anchor_mask]

    b = gt_box.shape[1]
    gx = gt_box[:, :, 0] * w
    gy = gt_box[:, :, 1] * h
    gw = gt_box[:, :, 2]
    gh = gt_box[:, :, 3]
    valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)
    gi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
    gj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)

    # best anchor per gt by IoU of (w, h) only, among the masked anchors
    ious = []
    for (aw, ah) in masked:
        aw_n, ah_n = aw / input_size, ah / input_size
        inter = jnp.minimum(gw, aw_n) * jnp.minimum(gh, ah_n)
        union = gw * gh + aw_n * ah_n - inter
        ious.append(inter / jnp.maximum(union, 1e-9))
    best_a = jnp.argmax(jnp.stack(ious, -1), -1)          # [N, B]

    loss = jnp.zeros((n,), jnp.float32)
    obj_target = jnp.zeros((n, an_num, h, w))
    bi = jnp.arange(n)[:, None].repeat(b, 1)
    score = (gt_score if use_score else jnp.ones((n, b)))
    score = jnp.where(valid, score, 0.0)

    tw_sel = jnp.zeros((n, b))
    th_sel = jnp.zeros((n, b))
    for a_idx, (aw, ah) in enumerate(masked):
        sel = best_a == a_idx
        tw_sel = jnp.where(sel, jnp.log(jnp.maximum(
            gw * input_size / aw, 1e-9)), tw_sel)
        th_sel = jnp.where(sel, jnp.log(jnp.maximum(
            gh * input_size / ah, 1e-9)), th_sel)

    def gather_pred(p):
        return p[bi, best_a, gj, gi]                      # [N, B]

    def sce(logit, label):
        # numerically-stable sigmoid cross-entropy on raw logits
        # (reference SigmoidCrossEntropy, yolo_loss_kernel.cc:33)
        return (jnp.maximum(logit, 0) - logit * label
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    tx = gx - gi
    ty = gy - gj
    # reference CalcBoxLocationLoss: SCE on raw x/y logits, L1 on raw w/h,
    # all scaled by (2 - gw*gh) * score
    box_scale = (2.0 - gw * gh) * score
    x_logit = x[:, :, 0]
    y_logit = x[:, :, 1]
    l_xy = (sce(gather_pred(x_logit), tx)
            + sce(gather_pred(y_logit), ty)) * box_scale
    l_wh = (jnp.abs(gather_pred(pw_raw) - tw_sel)
            + jnp.abs(gather_pred(ph_raw) - th_sel)) * box_scale

    # objectness: positive cells carry the per-gt (mixup) score; negatives
    # everywhere EXCEPT cells whose predicted box overlaps any gt above
    # ignore_thresh (reference yolo_loss ignore mask + CalcObjnessLoss)
    obj_target = obj_target.at[bi, best_a, gj, gi].max(
        jnp.where(valid, score, 0.0))
    # decode every predicted box [N, A, H, W, 4] (normalized xywh)
    cell_x = jnp.arange(w)[None, None, None, :]
    cell_y = jnp.arange(h)[None, None, :, None]
    pred_cx = (px + cell_x) / w
    pred_cy = (py + cell_y) / h
    aw_arr = jnp.asarray([a[0] for a in masked])[None, :, None, None]
    ah_arr = jnp.asarray([a[1] for a in masked])[None, :, None, None]
    pred_w = jnp.exp(jnp.clip(pw_raw, -10, 10)) * aw_arr / input_size
    pred_h = jnp.exp(jnp.clip(ph_raw, -10, 10)) * ah_arr / input_size
    # IoU of every predicted box against every gt: [N, A, H, W, B]
    gt_cx = (gt_box[:, :, 0])[:, None, None, None, :]
    gt_cy = (gt_box[:, :, 1])[:, None, None, None, :]
    gt_w = gw[:, None, None, None, :]
    gt_h = gh[:, None, None, None, :]
    ix = jnp.maximum(
        0.0,
        jnp.minimum(pred_cx[..., None] + pred_w[..., None] / 2,
                    gt_cx + gt_w / 2)
        - jnp.maximum(pred_cx[..., None] - pred_w[..., None] / 2,
                      gt_cx - gt_w / 2))
    iy = jnp.maximum(
        0.0,
        jnp.minimum(pred_cy[..., None] + pred_h[..., None] / 2,
                    gt_cy + gt_h / 2)
        - jnp.maximum(pred_cy[..., None] - pred_h[..., None] / 2,
                      gt_cy - gt_h / 2))
    inter = ix * iy
    union = (pred_w * pred_h)[..., None] + gt_w * gt_h - inter
    pred_iou = jnp.where(valid[:, None, None, None, :],
                         inter / jnp.maximum(union, 1e-9), 0.0)
    pos = obj_target > 1e-5
    ignore = (pred_iou.max(-1) > ignore_thresh) & ~pos
    # positive: SCE(logit, 1) * score; negative (non-ignored): SCE(logit, 0)
    l_obj_map = jnp.where(
        pos, sce(obj_logit, 1.0) * obj_target,
        jnp.where(ignore, 0.0, sce(obj_logit, 0.0)))
    l_obj = l_obj_map.sum(axis=(1, 2, 3))

    # reference: smooth_weight = min(1/class_num, 1/40) (yolo_loss_kernel.cc:215)
    smooth = min(1.0 / class_num, 1.0 / 40.0) if use_label_smooth else 0.0
    cls_t = jnp.full((n, b, class_num), smooth)
    lab = jnp.clip(gt_label.astype(jnp.int32), 0, class_num - 1)
    cls_t = cls_t.at[bi, jnp.arange(b)[None, :].repeat(n, 0), lab].set(
        1.0 - smooth)
    cls_pred = cls_logit[bi, best_a, :, gj, gi]           # [N, B, C]
    cls_ce = sce(cls_pred, cls_t)
    l_cls = (cls_ce.sum(-1) * score).sum(-1)

    loss = (l_xy + l_wh).sum(-1) + l_obj + l_cls
    return loss


defprim("yolo_loss_p", _yolo_loss_fwd)

__all__ += ["RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D", "matrix_nms",
            "generate_proposals", "yolo_loss"]
