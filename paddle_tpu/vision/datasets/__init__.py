"""paddle.vision.datasets parity (offline).

Reference: python/paddle/vision/datasets/ (MNIST/FashionMNIST read
idx-ubyte files, Cifar10/100 read the pickled batch tarball). This
environment has no network, so ``download=True`` raises with instructions;
the loaders read the standard file formats from ``image_path``/``data_file``
like the reference does after its download step. ``FakeData`` generates
deterministic synthetic batches for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataloader import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: download is unavailable in this environment; place the "
        "standard dataset files locally and pass their paths")


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py — idx-ubyte reader."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None:
            if download:
                _no_download(type(self).__name__)
            raise ValueError("image_path and label_path are required "
                             "(no auto-download here)")
        self.mode = mode
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, 1).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # CHW
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """Reference: vision/datasets/cifar.py — pickled-batch tar reader."""

    _flag = b"labels"
    _prefix = "data_batch"
    _test = "test_batch"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                _no_download(type(self).__name__)
            raise ValueError("data_file (cifar tar.gz) is required")
        self.mode = mode
        self.transform = transform
        self.data = []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames()
                     if ((self._prefix in n) if mode == "train"
                         else (self._test in n))]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name), encoding="bytes")
                for img, label in zip(batch[b"data"], batch[self._flag]):
                    self.data.append((img, int(label)))

    def __getitem__(self, idx):
        img, label = self.data[idx]
        img = np.asarray(img, dtype=np.float32).reshape(3, 32, 32)
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, np.int64(label)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _flag = b"fine_labels"
    _prefix = "train"
    _test = "test"


class FakeData(Dataset):
    """Deterministic synthetic image dataset (shape like ImageNet/MNIST) —
    for tests and throughput benchmarks without any files."""

    def __init__(self, size=256, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, label

    def __len__(self):
        return self.size


class DatasetFolder(Dataset):
    """Directory-per-class dataset (reference: vision/datasets/folder.py).
    Files load through vision.image_load; a ``loader`` overrides."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        extensions = tuple(extensions) if extensions else (
            ".jpg", ".jpeg", ".png", ".bmp", ".npy")
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if loader is None:
            from .. import image_load

            loader = image_load
        self.loader = loader
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file is not None
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image folder without labels
    (reference: vision/datasets/folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        extensions = tuple(extensions) if extensions else (
            ".jpg", ".jpeg", ".png", ".bmp", ".npy")
        if loader is None:
            from .. import image_load

            loader = image_load
        self.loader = loader
        self.transform = transform
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file is not None
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford-102 flowers (reference: vision/datasets/flowers.py). Reads
    the tarball + .mat labels from local files; synthetic mode generates
    deterministic images for CI."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None,
                 synthetic=True):
        if not synthetic:
            _no_download("Flowers")
        from ...dataset.common import _synthetic_rng

        rng = _synthetic_rng(f"vision-flowers-{mode}")
        n = 128 if mode == "train" else 32
        self.images = rng.random((n, 3, 32, 32)).astype("float32")
        self.labels = rng.integers(0, 102, size=n)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference: vision/datasets/voc2012.py).
    Local-archive or deterministic synthetic (image, seg-mask) pairs."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, synthetic=True):
        if not synthetic:
            _no_download("VOC2012")
        from ...dataset.common import _synthetic_rng

        rng = _synthetic_rng(f"voc2012-{mode}")
        n = 64 if mode == "train" else 16
        self.images = rng.random((n, 3, 32, 32)).astype("float32")
        self.masks = rng.integers(0, 21, size=(n, 32, 32)).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


__all__ += ["DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]
