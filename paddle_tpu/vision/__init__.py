"""paddle.vision parity. Reference: python/paddle/vision/__init__.py."""
from . import datasets, models, transforms  # noqa: F401
from .models import *  # noqa: F401,F403
from . import ops  # noqa: F401
