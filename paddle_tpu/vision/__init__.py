"""paddle.vision parity. Reference: python/paddle/vision/__init__.py."""
from . import datasets, models, transforms  # noqa: F401
from .models import *  # noqa: F401,F403
from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """Reference: vision/image.py set_image_backend ('pil' | 'cv2')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor", "numpy"):
        raise ValueError(f"invalid backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Reference: vision/image.py image_load — reads an image file with the
    active backend; numpy fallback covers raw arrays saved via np.save."""
    backend = backend or _image_backend
    if backend in ("pil",):
        try:
            from PIL import Image

            return Image.open(path)
        except ImportError:
            backend = "numpy"
    if backend == "cv2":
        raise RuntimeError("cv2 is not available in this environment")
    import numpy as np

    return np.load(path) if str(path).endswith(".npy") else np.fromfile(
        path, dtype="uint8")
