"""paddle.framework parity (io + misc)."""
from .io_ import save, load
