from .io_ import save, load
