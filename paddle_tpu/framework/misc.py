"""Top-level framework utilities.

Reference: python/paddle/framework/ (dtype exposure, iinfo/finfo —
framework/dtype.py), random-state API (framework/random.py), LazyGuard
(nn/initializer/lazy_init.py), create_parameter (tensor/creation.py).
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.dtype import convert_dtype

__all__ = [
    "dtype", "iinfo", "finfo", "LazyGuard", "create_parameter",
    "get_rng_state", "set_rng_state", "get_cuda_rng_state",
    "set_cuda_rng_state",
]


def dtype(d):
    """paddle.dtype — the canonical dtype object (numpy dtype here)."""
    return convert_dtype(d)


class _IInfo:
    def __init__(self, np_info):
        self.min = int(np_info.min)
        self.max = int(np_info.max)
        self.bits = int(np_info.bits)
        self.dtype = str(np_info.dtype)

    def __repr__(self):
        return f"iinfo(min={self.min}, max={self.max}, bits={self.bits}, dtype={self.dtype})"


class _FInfo:
    def __init__(self, np_info):
        self.min = float(np_info.min)
        self.max = float(np_info.max)
        self.eps = float(np_info.eps)
        self.tiny = float(np_info.tiny)
        self.smallest_normal = float(np_info.tiny)
        self.resolution = float(np_info.resolution)
        self.bits = int(np_info.bits)
        self.dtype = str(np_info.dtype)

    def __repr__(self):
        return (f"finfo(min={self.min}, max={self.max}, eps={self.eps}, "
                f"bits={self.bits}, dtype={self.dtype})")


def iinfo(d):
    """Reference: paddle.iinfo (framework/dtype.py)."""
    return _IInfo(np.iinfo(convert_dtype(d)))


def finfo(d):
    """Reference: paddle.finfo. Handles bfloat16 via jax's dtype info."""
    nd = convert_dtype(d)
    if str(nd) == "bfloat16":
        import jax.numpy as jnp
        import ml_dtypes

        return _FInfo(ml_dtypes.finfo(jnp.bfloat16))
    return _FInfo(np.finfo(nd))


class LazyGuard:
    """Reference: paddle.LazyGuard (nn/initializer/lazy_init.py) — delays
    parameter initialization until first use. TPU build: parameter arrays
    are created lazily by jax anyway (no device commit until consumed);
    the guard records its active window for API parity."""

    _active = False

    def __enter__(self):
        type(self)._active = True
        return self

    def __exit__(self, *exc):
        type(self)._active = False
        return False


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Reference: paddle.create_parameter (tensor/creation.py) — free
    parameter with ParamAttr/initializer semantics."""
    from ..nn.layer import Layer

    holder = Layer()
    p = holder.create_parameter(shape=list(shape), attr=attr,
                                dtype=str(convert_dtype(dtype)),
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        p.name = name
    return p


def get_rng_state(device=None):
    """Reference: paddle.get_rng_state — list of generator states."""
    from ..core import generator

    return [generator.default_generator().get_state()]


def set_rng_state(state_list, device=None):
    from ..core import generator

    states = state_list if isinstance(state_list, (list, tuple)) else [state_list]
    generator.default_generator().set_state(states[0])


def get_cuda_rng_state():
    """CUDA alias — one accelerator stream on TPU."""
    return get_rng_state()


def set_cuda_rng_state(state_list):
    set_rng_state(state_list)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Reference: paddle.set_printoptions (tensor/to_string.py:38). Tensor
    repr renders through numpy, so this maps onto numpy printoptions."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    np.set_printoptions(**kw)


def check_shape(shape):
    """Reference: utils/layers_utils.py:468 — validate a shape argument."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int, np.integer)) and s is not None:
                from ..core.tensor import Tensor

                if not isinstance(s, Tensor):
                    raise TypeError(
                        f"shape entries must be int/Tensor, got {type(s)}")
    return shape


def disable_signal_handler():
    """Reference: paddle.disable_signal_handler — the TPU build installs no
    signal handlers, so this is a recorded no-op."""


_STATIC_MODE = False


def enable_static():
    """Reference: paddle.enable_static — the TPU build's static path is the
    Program-capture layer (paddle_tpu.static); this flag makes
    in_dynamic_mode() report static."""
    global _STATIC_MODE
    _STATIC_MODE = True


def disable_static():
    global _STATIC_MODE
    _STATIC_MODE = False


def in_static_mode() -> bool:
    return _STATIC_MODE


__all__ += [
    "set_printoptions", "check_shape", "disable_signal_handler",
    "enable_static", "disable_static", "in_static_mode",
]
