"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:773 (save) / :1020 (load) — pickles
nested state dicts of Tensors. Here Tensors serialize as numpy arrays inside
a pickle, so checkpoints are portable off-TPU.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np


class _TensorPayload:
    """Pickle-stable wrapper (bfloat16 etc. stored via raw bytes)."""

    __slots__ = ("bytes", "dtype", "shape")

    def __init__(self, arr: np.ndarray):
        self.dtype = str(arr.dtype)
        self.shape = arr.shape
        self.bytes = arr.tobytes()

    def to_numpy(self) -> np.ndarray:
        import ml_dtypes  # noqa: F401  (registers bfloat16 etc. with numpy)

        return np.frombuffer(self.bytes, dtype=np.dtype(self.dtype)).reshape(self.shape)


def _pack(obj):
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    from ..core.tensor import Tensor

    if isinstance(obj, _TensorPayload):
        arr = obj.to_numpy()
        return arr if return_numpy else Tensor._from_value(jnp.asarray(arr))
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
