"""Model export — ``paddle.onnx`` parity surface.

Reference: python/paddle/onnx/export.py (paddle.onnx.export delegating to
paddle2onnx). In this framework the portable interchange format is
StableHLO (what XLA consumes natively and what jax.export serializes with
compatibility guarantees); ``export`` emits it alongside the parameters.
Actual .onnx serialization additionally needs the ``onnx`` package, which
is not part of this environment — requesting it raises with instructions
rather than writing a file in the wrong format."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, format="stablehlo",
           **configs):
    """Export ``layer`` for inference.

    format="stablehlo" (default): writes ``<path>.pdmodel`` via jit.save —
    parameters plus a serialized StableHLO forward for the given
    input_spec — loadable with paddle_tpu.jit.load and the inference
    Predictor on any XLA backend.

    format="onnx": reference behavior; requires the ``onnx`` package.
    """
    if format == "onnx":
        try:
            import onnx  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "ONNX serialization requires the 'onnx' package, which is "
                "not installed in this environment. Use the default "
                "format='stablehlo' export (loadable via paddle_tpu.jit.load "
                "/ inference.Predictor), or install onnx."
            ) from e
        raise NotImplementedError(
            "onnx graph conversion is not implemented; export StableHLO "
            "instead (the TPU-native interchange format)"
        )
    if format != "stablehlo":
        raise ValueError(f"unknown export format: {format}")
    if input_spec is None:
        raise ValueError(
            "export requires input_spec (shapes/dtypes of the forward inputs)"
        )
    from . import jit

    base = path[:-len(".onnx")] if path.endswith(".onnx") else path
    payload = jit.save(layer, base, input_spec=input_spec, **configs)
    out_path = base + ".pdmodel" if not base.endswith(".pdmodel") else base
    if "serialized" not in payload:
        # jit.save is best-effort (params always persist); export promises a
        # SERVABLE artifact — remove the params-only file and fail loudly
        import os

        try:
            os.remove(out_path)
        except OSError:
            pass
        raise RuntimeError(
            "StableHLO export of the forward failed. Cause: "
            f"{payload.get('export_error', 'unknown')}"
        )
    return out_path
