"""DataLoader worker-process side. Deliberately jax-free: spawn startup
must not pay a backend import for every worker (reference worker
processes likewise never touch device state —
python/paddle/io/dataloader/worker.py _worker_loop)."""
from __future__ import annotations

import numpy as np


class WorkerInfo:
    __slots__ = ("id", "num_workers", "seed", "dataset")

    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.seed = wid
        self.dataset = dataset


_worker_info = None  # set inside worker processes


def numpy_collate(batch):
    """Stack samples into host numpy batches (worker-side half of the
    default collate; Tensors are handled by the parent-side wrapper in
    dataloader.py to keep this module jax-free)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: numpy_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(
            numpy_collate(list(fields)) for fields in zip(*batch)
        )
    # fallback for framework Tensors (and anything array-like) without
    # importing the Tensor type here
    if hasattr(sample, "numpy"):
        return np.stack([np.asarray(s.numpy()) for s in batch])
    raise TypeError(f"cannot collate {type(sample)}")


def worker_loop(dataset, worker_init_fn, worker_id, num_workers,
                index_q, result_q):
    """Pull (seq, idxs) jobs, push (seq, numpy batch, error)."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        job = index_q.get()
        if job is None:
            return
        seq, idxs = job
        try:
            batch = numpy_collate([dataset[i] for i in idxs])
            result_q.put((seq, batch, None))
        except Exception:
            import traceback

            result_q.put((seq, None, traceback.format_exc()))
