"""paddle.io parity surface. Reference: python/paddle/io/__init__.py."""
from .dataloader import (
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split, Sampler, SequenceSampler,
    RandomSampler, WeightedRandomSampler, BatchSampler, SubsetRandomSampler,
    DistributedBatchSampler, DataLoader, default_collate_fn, get_worker_info,
)
