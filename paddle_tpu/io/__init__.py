"""placeholder."""
