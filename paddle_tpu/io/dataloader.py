"""Dataset / DataLoader.

Reference: python/paddle/io/ (Dataset, IterableDataset, TensorDataset,
Sampler/RandomSampler/BatchSampler, DataLoader with worker processes —
reader/dataloader_iter.py). TPU design: host-side numpy batching with a
background prefetch thread; device transfer happens lazily on first op (or
eagerly via places). Multi-process workers use a thread pool instead — the
GIL is released inside numpy/jax host ops, and TPU input pipelines are
host-bound on decode, not on Python loops at this scale.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from .. import observability as _obs
from ..core import generator
from ..core.tensor import Tensor

# -- prefetch-ring telemetry (ROADMAP open item) ----------------------------
# queue_depth is sampled at every consumer pop (how many batches were
# ready = how far ahead the producers run); wait_seconds is the time the
# training loop spent blocked on input — the "is the step loop
# input-bound?" gauge. Labeled by ring: python (thread prefetcher),
# native (csrc ring), mp (worker processes).
_obs_state = _obs.state
_M_QUEUE_DEPTH = _obs.gauge(
    "io.queue_depth",
    "prefetched batches ready at the last consumer pop, by ring "
    "(python | native | mp)")
_M_WAIT_SECONDS = _obs.histogram(
    "io.wait_seconds",
    "wall seconds the consumer blocked waiting for the next batch, by "
    "ring (python | native | mp)")
_M_BATCHES = _obs.counter(
    "io.batches_delivered",
    "batches handed to the training loop, by ring (python | native | mp)")


def _record_pop(ring: str, depth: int, waited: float):
    _M_QUEUE_DEPTH.set(depth, ring=ring)
    _M_WAIT_SECONDS.observe(waited, ring=ring)
    _M_BATCHES.inc(ring=ring)


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Tensor]):
        lens = {len(t) for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        # fraction form
        if all(0 < l < 1 for l in lengths):
            lengths = [int(l * total) for l in lengths]
            lengths[-1] = total - sum(lengths[:-1])
        else:
            raise ValueError("lengths must sum to dataset size")
    perm = np.random.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Random permutation over a fixed index subset
    (reference: io/dataloader/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError("indices must not be empty")
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, self.replacement, p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler — shards indices across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as dist_env

            num_replicas = num_replicas or dist_env.get_world_size()
            rank = rank if rank is not None else dist_env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank :: self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# single collate ladder, shared with worker processes (the jax-free
# module handles Tensors through its `.numpy()` duck-typed fallback)
from ._mp_worker import numpy_collate as _numpy_collate  # noqa: E402


def _tensorize(obj):
    """Consumer-side half: wrap numpy payloads into Tensors."""
    if isinstance(obj, np.ndarray):
        return Tensor._from_value(obj)
    if isinstance(obj, dict):
        return {k: _tensorize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)) and obj and \
            not isinstance(obj[0], (str, bytes)):
        return type(obj)(_tensorize(v) for v in obj)
    return obj


def default_collate_fn(batch):
    """Stack samples → numpy batches → Tensors (reference:
    io/dataloader/collate.py default_collate_fn)."""
    return _tensorize(_numpy_collate(batch))


def _native_queue(capacity: int):
    """Native C++ prefetch ring (csrc/ptpu_queue.cc), or None.

    TPU-native analog of the reference's buffered reader / blocking queue
    between data-feed workers and the trainer (framework/data_feed.cc):
    workers push pickled numpy batches, the step loop pops and tensorizes.
    """
    try:
        from paddle_tpu import native

        if native.is_available():
            return native.BlockingQueue(capacity)
    except Exception:
        pass
    return None


class _PrefetchIter:
    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = index_iter
        cap = max(2, loader.prefetch_factor)
        # Native ring only carries picklable payloads, i.e. the default
        # (numpy) collate path; custom collate_fns stay on the Python queue.
        self.nq = _native_queue(cap) if loader.collate_fn is None and \
            getattr(loader, "use_buffer_reader", True) else None
        self.q: "queue.Queue" = queue.Queue(maxsize=cap) \
            if self.nq is None else None
        self.done = object()
        self.workers: List[threading.Thread] = []
        n = max(1, loader.num_workers)
        self.lock = threading.Lock()
        self._launch(n)

    def _launch(self, n):
        import pickle

        def work():
            # thread fallback still honors per-worker init (single worker
            # thread -> id 0)
            if getattr(self.loader, "worker_init_fn", None) is not None:
                self.loader.worker_init_fn(0)
            while True:
                with self.lock:
                    try:
                        idxs = next(self.index_iter)
                    except StopIteration:
                        break
                batch = [self.loader.dataset[i] for i in idxs]
                if self.nq is not None:
                    payload = pickle.dumps(
                        _numpy_collate(batch), pickle.HIGHEST_PROTOCOL
                    )
                    try:
                        self.nq.push(b"B" + payload)
                    except RuntimeError:  # consumer closed early
                        return
                else:
                    collate = self.loader.collate_fn or default_collate_fn
                    self.q.put(collate(batch))
            if self.nq is not None:
                try:
                    self.nq.push(b"D")
                except RuntimeError:
                    pass
            else:
                self.q.put(self.done)

        for _ in range(1):  # single prefetch thread preserves batch order
            t = threading.Thread(target=work, daemon=True)
            t.start()
            self.workers.append(t)

    def __iter__(self):
        return self

    def __next__(self):
        import time as _time

        rec = _obs_state.on  # latch: obs toggled mid-pop must not record
        t0 = _time.perf_counter() if rec else 0.0
        if self.nq is not None:
            import pickle

            item = self.nq.pop()
            if item is None or item[:1] == b"D":
                raise StopIteration
            if rec:
                _record_pop("native", len(self.nq),
                            _time.perf_counter() - t0)
            return _tensorize(pickle.loads(item[1:]))
        item = self.q.get()
        if item is self.done:
            raise StopIteration
        if rec:
            _record_pop("python", self.q.qsize(),
                        _time.perf_counter() - t0)
        return item

    def __del__(self):
        try:
            if self.nq is not None:
                self.nq.close()
        except Exception:
            pass


class _MultiprocessIter:
    """Worker PROCESSES + in-order reassembly.

    The reference runs worker processes (io/dataloader/dataloader_iter.py
    _DataLoaderIterMultiProcess); thread workers are GIL-bound for
    Python-heavy __getitem__. Jobs are sequence-numbered and results
    reordered in the parent, so batch order is identical to the
    single-process loader regardless of worker scheduling."""

    def __init__(self, loader, index_iter, persistent=False):
        self.loader = loader
        self.index_iter = index_iter
        self.persistent = persistent
        n = max(1, loader.num_workers)
        # Plain fork is NOT safe here: the training process is heavily
        # multithreaded (XLA runtime), and a fork can inherit a lock held
        # mid-operation — observed as futex-deadlocked workers. _mp_context
        # therefore uses spawn (see its docstring for why forkserver was
        # rejected too); the startup cost is amortized by
        # persistent_workers.
        ctx = _mp_context()
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        from ._mp_worker import worker_loop

        self.procs = []
        for wid in range(n):
            p = ctx.Process(
                target=worker_loop,
                args=(loader.dataset, loader.worker_init_fn, wid, n,
                      self.index_q, self.result_q),
                daemon=True)
            p.start()
            self.procs.append(p)
        self._next_seq = 0      # next batch to hand out
        self._sent = 0          # jobs dispatched
        self._exhausted = False
        self._pending = {}      # seq -> batch (out-of-order arrivals)
        self._max_inflight = n * max(2, loader.prefetch_factor)
        self._fill()

    def _fill(self):
        while (not self._exhausted
               and self._sent - self._next_seq < self._max_inflight):
            try:
                idxs = next(self.index_iter)
            except StopIteration:
                self._exhausted = True
                break
            self.index_q.put((self._sent, list(idxs)))
            self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        import queue as _q
        import time as _time

        if self._next_seq >= self._sent and self._exhausted:
            if not self.persistent:
                self._shutdown()
            raise StopIteration
        rec = _obs_state.on  # latch: obs toggled mid-pop must not record
        t0 = _time.perf_counter() if rec else 0.0
        stalled = 0.0
        while self._next_seq not in self._pending:
            try:
                seq, batch, err = self.result_q.get(timeout=5.0)
            except _q.Empty:
                # a worker killed by the OS (OOM, segfault in native code)
                # posts nothing: surface a diagnosis instead of hanging
                dead = [p.pid for p in self.procs if not p.is_alive()]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited abnormally "
                        "(killed?) without reporting a result")
                stalled += 5.0
                if stalled >= 120.0:
                    # workers alive but silent: deadlock/stuck __getitem__
                    # — fail loudly rather than hang the training job
                    self._shutdown()
                    raise RuntimeError(
                        "DataLoader workers produced no batch for 120s "
                        "(alive but stalled)")
                continue
            stalled = 0.0
            if err is not None:
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._pending[seq] = batch
        batch = self._pending.pop(self._next_seq)
        self._next_seq += 1
        if rec:
            # depth = out-of-order arrivals already reassembled and
            # waiting, i.e. how far ahead the worker pool runs
            _record_pop("mp", len(self._pending),
                        _time.perf_counter() - t0)
        self._fill()
        return _tensorize(batch)

    def _attach(self, index_iter):
        """Persistent-worker epoch restart: reuse the live worker pool
        with a fresh index stream (reference persistent_workers).

        If the previous epoch was abandoned mid-iteration (``break``),
        jobs from the old index stream may still be queued or in flight;
        drain and discard them first so the new epoch never yields stale
        batches (mirrors the reference iterator reset)."""
        import queue as _q

        while self._next_seq < self._sent:
            if self._next_seq in self._pending:
                self._pending.pop(self._next_seq)
                self._next_seq += 1
                continue
            try:
                seq, _batch, _err = self.result_q.get(timeout=30.0)
            except _q.Empty:
                dead = [p.pid for p in self.procs if not p.is_alive()]
                self._shutdown()
                raise RuntimeError(
                    "DataLoader worker pool stalled while draining stale "
                    f"jobs on epoch restart (dead workers: {dead})")
            self._pending[seq] = None
        self._pending.clear()
        self.index_iter = index_iter
        self._exhausted = False
        self._fill()

    def _shutdown(self):
        for _ in self.procs:
            try:
                self.index_q.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self.procs = []

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


def _mp_context():
    """spawn, deliberately. fork from this (XLA-threaded) process can
    inherit a lock held mid-operation — observed as futex-deadlocked
    workers under the full test suite; forkserver routes through spawn's
    main-module re-preparation anyway. spawn's per-worker startup cost is
    amortized by persistent_workers."""
    import multiprocessing as mp

    return mp.get_context("spawn")


def _mp_usable(loader) -> bool:
    """Process workers need the default (numpy) collate and a picklable
    dataset (forkserver/spawn both pickle job state); otherwise fall back
    to the thread prefetcher."""
    if loader.collate_fn is not None:
        return False
    import pickle

    try:
        pickle.dumps((loader.dataset, loader.worker_init_fn))
        return True
    except Exception:
        return False


class DataLoader:
    """Reference: python/paddle/io/DataLoader (places/return_list args kept
    for compatibility; on TPU there is one process per host, not per chip).
    num_workers > 0 spawns worker PROCESSES (numpy collate in workers,
    in-order reassembly in the parent); unpicklable datasets or custom
    collate_fns fall back to the thread prefetcher."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif not self._iterable_mode and batch_size is not None:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )
        else:
            self.batch_sampler = None
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("length unknown for iterable dataset")

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:
            # batch_size=None → sample-at-a-time
            def gen():
                collate = self.collate_fn or (lambda x: x)
                for i in range(len(self.dataset)):
                    yield collate(self.dataset[i])

            return gen()
        if self.num_workers and self.num_workers > 0:
            if _mp_usable(self):
                if self.persistent_workers:
                    pool = getattr(self, "_persistent_pool", None)
                    if pool is not None and pool.procs:
                        pool._attach(iter(self.batch_sampler))
                        return pool
                    pool = _MultiprocessIter(self, iter(self.batch_sampler),
                                             persistent=True)
                    self._persistent_pool = pool
                    return pool
                return _MultiprocessIter(self, iter(self.batch_sampler))
            return _PrefetchIter(self, iter(self.batch_sampler))

        def gen():
            collate = self.collate_fn or default_collate_fn
            for idxs in self.batch_sampler:
                yield collate([self.dataset[i] for i in idxs])

        return gen()

    def _iter_iterable(self):
        collate = self.collate_fn or default_collate_fn
        if self.batch_size is None:
            yield from iter(self.dataset)
            return
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield collate(batch)
                batch = []
        if batch and not self.drop_last:
            yield collate(batch)


def get_worker_info():
    """Inside a worker process: (id, num_workers, dataset); else None
    (reference io/dataloader/worker.py get_worker_info)."""
    from ._mp_worker import _worker_info

    return _worker_info
