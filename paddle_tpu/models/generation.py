"""Incremental decoding (KV-cache generation) for the causal LMs.

Reference surface: PaddleNLP's ``model.generate`` (greedy / sampling
over a cached decoder) built on the serving ops the core repo ships —
masked_multihead_attention (single-step decode over a dense KV cache,
incubate/nn/functional/masked_multihead_attention.py:19) and the
block/paged variants. The core reference also exposes
``paddle.nn.BeamSearchDecoder``/``dynamic_decode`` (nn/decode.py) for
seq2seq; THIS module is the decoder-only LLM path.

TPU-first design: the ENTIRE decode loop is one jitted program — a
``lax.scan`` over ``max_new_tokens`` whose carry holds the dense KV
cache ``[L, B, S_max, kvh, dh]``; each tick is a single-token forward
through the transformer stack with the attention reading the cache
(static shapes throughout, one compile, zero host round-trips between
tokens — on a tunneled chip a per-token dispatch would cost ~1s/token).
Prefill runs the prompt through the same cached step with T=prompt_len
and a causal mask.

The math mirrors models/llama.py exactly (same rope tables via
incubate's ``_rope_tables``/``rotate_half``); the test suite pins the
cached greedy path token-for-token against the model's own full-prefix
forward, so any architecture drift fails loudly. Families: Llama, GPT,
and ERNIE-MoE (per-step expert routing through the same index-dispatch
program the training forward uses, EVAL routing).

Supports: greedy, temperature / top-k / top-p sampling with
repetition_penalty / min_length, eos early-stop (fixed-length scan
with post-eos masking — compiler-friendly control flow instead of a
data-dependent loop), BEAM SEARCH with GNMT length_penalty,
LEFT-PADDED mixed-length prompts (``pad_token_id=...``: per-row
rope/position offsets + a pad-aware visibility mask, every row pinned
against its own full-prefix oracle in tests), a PAGED block-KV-cache
decode path (``paged=True``, Llama and GPT families) that drives the
same ``block_mha_p`` program the serving op
``incubate.nn.functional.block_multihead_attention`` exposes
(reference: incubate/nn/functional/block_multihead_attention.py:19),
and SPECULATIVE draft-and-verify decoding (``generate_speculative``,
output exactly equal to the target's greedy by construction).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["generate", "generate_speculative"]


def _llama_decode_params(model):
    """Closure-friendly views of the model's parameter arrays."""
    cfg = model.config
    layers = []
    for layer in model.llama.layers:
        a, m = layer.self_attn, layer.mlp
        layers.append(dict(
            ln1=layer.input_layernorm.weight._value,
            wq=a.q_proj.weight._value, wk=a.k_proj.weight._value,
            wv=a.v_proj.weight._value, wo=a.o_proj.weight._value,
            ln2=layer.post_attention_layernorm.weight._value,
            wg=m.gate_proj.weight._value, wu=m.up_proj.weight._value,
            wd=m.down_proj.weight._value,
        ))
    return dict(
        embed=model.llama.embed_tokens.weight._value,
        norm=model.llama.norm.weight._value,
        head=model.lm_head.weight._value,
        layers=layers,
        nh=cfg.num_attention_heads, nkv=cfg.num_key_value_heads,
        dh=cfg.hidden_size // cfg.num_attention_heads,
        eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
    )


def _rms(h, g, eps, dtype):
    """RMSNorm in f32 — ONE implementation for the dense and paged
    decode paths so the norm math can't drift between them."""
    import jax.numpy as jnp
    from jax import lax

    h32 = h.astype(jnp.float32)
    y = h32 * lax.rsqrt(jnp.mean(h32 * h32, axis=-1, keepdims=True) + eps)
    return (y * g.astype(jnp.float32)).astype(dtype)


def _ln(h, g, bb, eps, dtype):
    """LayerNorm in f32 — shared by the dense and paged GPT paths."""
    import jax.numpy as jnp
    from jax import lax

    h32 = h.astype(jnp.float32)
    mu = jnp.mean(h32, axis=-1, keepdims=True)
    var = jnp.mean((h32 - mu) ** 2, axis=-1, keepdims=True)
    y = (h32 - mu) * lax.rsqrt(var + eps)
    return (y * g.astype(jnp.float32) + bb.astype(jnp.float32)).astype(dtype)


def _llama_ffn(h, lp, dtype):
    """SwiGLU MLP — shared by the dense and paged Llama paths."""
    import jax
    import jax.numpy as jnp

    return (jax.nn.silu((h @ lp["wg"]).astype(jnp.float32)).astype(dtype)
            * (h @ lp["wu"])) @ lp["wd"]


def _gpt_ffn(h, lp, dtype):
    """GELU MLP with biases — shared by the dense and paged GPT paths."""
    import jax
    import jax.numpy as jnp

    return jax.nn.gelu((h @ lp["w1"] + lp["b1"]).astype(jnp.float32),
                       approximate=False).astype(dtype) \
        @ lp["w2"] + lp["b2"]


def _cached_forward(p, tokens, caches, pos, s_max, pads=None,
                    return_all=False):
    """Forward ``tokens`` [B, T] through the stack at absolute positions
    ``pos..pos+T-1``, reading/updating the per-layer KV caches
    [B, S_max, kvh, dh]. Returns (last-position hidden [B, H], caches) —
    or every position's hidden [B, T, H] with ``return_all`` (the
    speculative verify pass needs all of them). Causal within the new
    tokens; full attention to everything cached before ``pos``.
    ``pads`` [B] (left-pad counts) offsets each row's rope positions and
    blanks its pad slots out of the visibility mask — the ragged-prompt
    path. ``pos`` may be a traced scalar (speculative decoding advances
    it dynamically)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..incubate.nn.functional import _rope_tables
    from ..incubate.nn.functional._rope_common import rotate_half

    b, t = tokens.shape
    nh, nkv, dh = p["nh"], p["nkv"], p["dh"]
    x = jnp.take(p["embed"], tokens, axis=0)          # [B, T, H]
    dtype = x.dtype

    def rms(h, g):
        return _rms(h, g, p["eps"], dtype)

    cos_full, sin_full = _rope_tables(s_max, dh, p["theta"], True,
                                      jnp.float32)
    positions = pos + jnp.arange(t)                   # absolute [T]
    if pads is None:
        cos = jnp.take(cos_full, positions, axis=0)[None, :, None, :]
        sin = jnp.take(sin_full, positions, axis=0)[None, :, None, :]
        # query i (absolute pos+i) may see cache slot j iff j <= pos+i
        slot = jnp.arange(s_max)[None, :]             # [1, S_max]
        visible = (slot <= positions[:, None])[None]  # [1, T, S_max]
    else:
        # per-row logical positions: absolute minus this row's pad run
        rel = jnp.maximum(positions[None, :] - pads[:, None], 0)  # [B, T]
        cos = jnp.take(cos_full, rel, axis=0)[:, :, None, :]
        sin = jnp.take(sin_full, rel, axis=0)[:, :, None, :]
        slot = jnp.arange(s_max)[None, None, :]
        visible = (slot <= positions[None, :, None]) \
            & (slot >= pads[:, None, None])           # [B, T, S_max]

    new_caches = []
    moe_statics = p.get("moe_statics")
    for li, (lp, cache) in enumerate(zip(p["layers"], caches)):
        h = rms(x, lp["ln1"])
        q = (h @ lp["wq"]).reshape(b, t, nh, dh)
        k = (h @ lp["wk"]).reshape(b, t, nkv, dh)
        v = (h @ lp["wv"]).reshape(b, t, nkv, dh)
        q = (q.astype(jnp.float32) * cos
             + rotate_half(q.astype(jnp.float32), True) * sin).astype(dtype)
        k = (k.astype(jnp.float32) * cos
             + rotate_half(k.astype(jnp.float32), True) * sin).astype(dtype)
        ctx, cache = _cached_attention(q, k, v, cache, pos, visible,
                                       nh // nkv)
        new_caches.append(cache)
        x = x + ctx @ lp["wo"]
        h2 = rms(x, lp["ln2"])
        if "moe" in lp:
            x = x + _moe_mlp(h2, lp, moe_statics[li], dtype)
        else:
            x = x + _llama_ffn(h2, lp, dtype)
    out = rms(x, p["norm"])
    return (out if return_all else out[:, -1, :]), new_caches


def _ernie_decode_params(model):
    """ERNIE-MoE views: Llama-style attention/norms, per-layer MLP is
    either the dense SwiGLU or a routed expert bank. Generation runs
    the gate's current-mode routing (eval: deterministic top-k, eval
    capacity factor). Expert CAPACITY is computed over the tokens of
    each decode call (prefill: B*prompt_len; steps: B) with the same
    shared formula as the training forward — so decode matches the
    model's full-prefix forward whenever no expert saturates (the
    oracle-pinned regime); when capacity binds, drop behavior is
    per-call, mirroring the reference's step-wise serving ops
    (masked/block MHA process only the step's tokens too)."""
    cfg = model.config
    layers = []
    moe_statics = []
    for layer in model.model.layers:
        a = layer.self_attn
        entry = dict(
            ln1=layer.input_layernorm.weight._value,
            wq=a.q_proj.weight._value, wk=a.k_proj.weight._value,
            wv=a.v_proj.weight._value, wo=a.o_proj.weight._value,
            ln2=layer.post_attention_layernorm.weight._value,
        )
        if layer.is_moe:
            gate, ex = layer.mlp.gate, layer.mlp.experts
            entry["moe"] = dict(
                gw=gate.weight._value, gb=gate.bias._value,
                w0=ex.w0._value, b0=ex.b0._value,
                w1=ex.w1._value, b1=ex.b1._value,
            )
            # routing statics live OUTSIDE the layer dict: the layers
            # list rides as a jit ARGUMENT, and a string inside it
            # would break tracing. _train_factor() already respects
            # gate.training (GShard: capacity[0] train / [1] eval;
            # Naive: flat factor).
            moe_statics.append((int(gate.topk),
                                float(gate._train_factor()),
                                ex.activation, bool(gate._normalize)))
        else:
            m = layer.mlp
            entry.update(wg=m.gate_proj.weight._value,
                         wu=m.up_proj.weight._value,
                         wd=m.down_proj.weight._value)
            moe_statics.append(None)
        layers.append(entry)
    return dict(
        embed=model.model.embed_tokens.weight._value,
        norm=model.model.norm.weight._value,
        head=model.lm_head.weight._value,
        layers=layers,
        moe_statics=tuple(moe_statics),   # hashable → static_cfg
        nh=cfg.num_attention_heads, nkv=cfg.num_key_value_heads,
        dh=cfg.hidden_size // cfg.num_attention_heads,
        eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
    )


def _moe_mlp(h, lp, statics, dtype):
    """Routed expert FFN for the decode mirror: EVAL GShard/naive
    routing (top-k softmax gate, deterministic) through the same
    index-dispatch program the model's own forward uses
    (moe_layer._moe_idx_ffn_fwd), so decode and full-prefix forward
    route identically."""
    import jax
    import jax.numpy as jnp

    from ..incubate.distributed.models.moe.moe_layer import _moe_idx_ffn_fwd

    from ..incubate.distributed.models.moe.gate import _capacity

    topk, factor, activation, normalize = statics
    m = lp["moe"]
    shape = h.shape
    x = h.reshape(-1, shape[-1])
    n, e = x.shape[0], m["gw"].shape[1]
    probs = jax.nn.softmax(
        (x @ m["gw"] + m["gb"]).astype(jnp.float32), axis=-1)
    # the SHARED capacity rule (gate._capacity) over THIS call's tokens
    cap = _capacity(n, e, topk, factor)
    out = _moe_idx_ffn_fwd(
        probs, x, m["w0"], m["b0"], m["w1"], m["b1"],
        jax.random.PRNGKey(0), k=topk, capacity=cap,
        activation=activation, normalize=normalize, random2=False)
    return out.astype(dtype).reshape(shape)


def _gpt_decode_params(model):
    """GPT-family views: learned positions, pre-LN, fused qkv, GELU."""
    cfg = model.config
    layers = []
    for layer in model.gpt.layers:
        a = layer.attn
        layers.append(dict(
            ln1_w=layer.norm1.weight._value, ln1_b=layer.norm1.bias._value,
            wqkv=a.qkv_proj.weight._value, bqkv=a.qkv_proj.bias._value,
            wo=a.out_proj.weight._value, bo=a.out_proj.bias._value,
            ln2_w=layer.norm2.weight._value, ln2_b=layer.norm2.bias._value,
            w1=layer.linear1.weight._value, b1=layer.linear1.bias._value,
            w2=layer.linear2.weight._value, b2=layer.linear2.bias._value,
        ))
    out = dict(
        embed=model.gpt.wte.weight._value,
        wpe=model.gpt.wpe.weight._value,
        normf_w=model.gpt.norm_f.weight._value,
        normf_b=model.gpt.norm_f.bias._value,
        layers=layers,
        nh=cfg.num_attention_heads, nkv=cfg.num_attention_heads,
        dh=cfg.hidden_size // cfg.num_attention_heads,
        eps=cfg.layer_norm_eps,
        # tied head: logits = hidden @ embed.T computed in-graph (a
        # materialized transpose would duplicate [V, H] on device)
        tied_head=bool(cfg.tie_word_embeddings),
        max_positions=int(cfg.max_position_embeddings),
    )
    if not cfg.tie_word_embeddings:
        out["head"] = model.lm_head.weight._value
    return out


def _gpt_cached_forward(p, tokens, caches, pos, s_max, pads=None,
                        return_all=False):
    """GPT block stack with a dense KV cache (pre-LN, learned
    positions); same contract as the llama `_cached_forward`."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, t = tokens.shape
    nh, dh = p["nh"], p["dh"]
    positions = pos + jnp.arange(t)
    if pads is None:
        wpe_rows = jnp.take(p["wpe"], positions, axis=0)[None, :, :]
        slot = jnp.arange(s_max)[None, :]
        visible = (slot <= positions[:, None])[None]  # [1, T, S_max]
    else:
        rel = jnp.maximum(positions[None, :] - pads[:, None], 0)  # [B, T]
        wpe_rows = jnp.take(p["wpe"], rel, axis=0)    # [B, T, H]
        slot = jnp.arange(s_max)[None, None, :]
        visible = (slot <= positions[None, :, None]) \
            & (slot >= pads[:, None, None])
    x = jnp.take(p["embed"], tokens, axis=0) + wpe_rows
    dtype = x.dtype

    def ln(h, g, bb):
        return _ln(h, g, bb, p["eps"], dtype)

    new_caches = []
    for lp, cache in zip(p["layers"], caches):
        h = ln(x, lp["ln1_w"], lp["ln1_b"])
        qkv = (h @ lp["wqkv"] + lp["bqkv"]).reshape(b, t, 3, nh, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        ctx, cache = _cached_attention(q, k, v, cache, pos, visible, 1)
        new_caches.append(cache)
        x = x + ctx @ lp["wo"] + lp["bo"]
        x = x + _gpt_ffn(ln(x, lp["ln2_w"], lp["ln2_b"]), lp, dtype)
    out = ln(x, p["normf_w"], p["normf_b"])
    return (out if return_all else out[:, -1, :]), new_caches


def _decode_family(model):
    """(params, cached_forward) for a supported causal-LM family."""
    if hasattr(model, "llama"):
        return _llama_decode_params(model), _cached_forward
    if hasattr(model, "gpt"):
        return _gpt_decode_params(model), _gpt_cached_forward
    from .ernie_moe import ErnieMoeForCausalLM

    if isinstance(model, ErnieMoeForCausalLM):
        return _ernie_decode_params(model), _cached_forward
    raise TypeError(
        f"generate() supports the Llama, GPT and ERNIE-MoE families; "
        f"got {type(model).__name__}")


def _head_logits(p, hidden):
    """LM-head logits; tied heads reuse the embedding in-graph."""
    if p.get("tied_head"):
        return hidden @ p["embed"].T
    return hidden @ p["head"]


def _cached_attention(q, k, v, cache, pos, visible, n_rep):
    """Shared cache-update + masked-softmax attention core: writes the
    new k/v at ``pos``, expands GQA kv heads by ``n_rep``, returns
    (context [B, T, nh*dh], updated cache). One implementation for
    every decode family so the mask/softmax/scale semantics can't
    drift."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, t = q.shape[:2]
    dh = q.shape[-1]
    ck, cv = cache
    # pos may be traced int32 (speculative decode); literal indices must
    # match its dtype exactly under jax_enable_x64
    z = jnp.int32(0)
    pos_i = jnp.asarray(pos, jnp.int32)
    ck = lax.dynamic_update_slice(ck, k, (z, pos_i, z, z))
    cv = lax.dynamic_update_slice(cv, v, (z, pos_i, z, z))
    kk = jnp.repeat(ck, n_rep, axis=2) if n_rep > 1 else ck
    vv = jnp.repeat(cv, n_rep, axis=2) if n_rep > 1 else cv
    logits = jnp.einsum("bthd,bshd->bhts", q, kk,
                        preferred_element_type=jnp.float32)
    logits = logits * (dh ** -0.5)
    # visible: [1 or B, T, S_max] — broadcast over heads
    logits = jnp.where(visible[:, None, :, :], logits,
                       jnp.float32(-1e30))
    attn = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    ctx = jnp.einsum("bhts,bshd->bthd", attn, vv).reshape(b, t, -1)
    return ctx, (ck, cv)


def _sample_token(logits, key, *, do_sample, temperature, top_k, top_p):
    """logits [B, V] -> token ids [B]."""
    import jax
    import jax.numpy as jnp

    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / jnp.float32(max(temperature, 1e-6))
    v = logits.shape[-1]
    if top_k and top_k > 0 and top_k < v:
        kth = jnp.sort(logits, axis=-1)[:, v - top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:  # top_p=0.0 means keep-only-the-best, not "off"
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix whose mass exceeds top_p (always
        # keep the best token)
        cutoff_idx = jnp.sum((cum < top_p).astype(jnp.int32), axis=-1)
        kth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _sample_slot_tokens(logits, temps, key):
    """Per-row mixed greedy/sampled decode for the serving engine:
    logits [B, V] and per-slot temperatures [B] (0.0 = greedy for that
    row) -> token ids [B]. Rows sample and argmax in one fused graph so
    a batch mixing greedy and sampled streams stays a single trace —
    this is the in-scan sampling step of the fused decode burst too,
    so it must remain shape-stable and key-pure."""
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(
        key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def _prep_decode(model, p, t0, max_new_tokens):
    """Shared decode-path setup (ONE copy for the greedy/beam/paged
    drivers): validate the learned-position table can hold the target
    length, split params into STATIC scalars (shapes depend on them)
    vs jit-argument arrays, and return the per-model jit cache."""
    max_pos = p.get("max_positions")
    if max_pos is not None and t0 + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) = "
            f"{t0 + max_new_tokens} exceeds the learned position table "
            f"(max_position_embeddings={max_pos}); jnp.take would "
            f"silently clamp and repeat the last position embedding")
    static_cfg = {k: v for k, v in p.items()
                  if not hasattr(v, "dtype") and not isinstance(v, list)}
    arrays = {k: v for k, v in p.items() if k not in static_cfg}
    cache = model.__dict__.setdefault("_generation_jit_cache", {})
    return static_cfg, arrays, cache


def _check_left_padded(ids_np, pad: int):
    """Leading-pad counts [B]; reject pads anywhere but a left run."""
    b, t0 = ids_np.shape
    is_pad = ids_np == pad
    pads = np.argmax(~is_pad, axis=1).astype(np.int32)
    pads = np.where(is_pad.all(axis=1), t0, pads)
    if (pads >= t0).any():
        raise ValueError("generate: a prompt row is entirely padding")
    for r in range(b):
        if is_pad[r, pads[r]:].any():
            raise ValueError(
                "generate(pad_token_id=...) expects LEFT-padded prompts; "
                f"row {r} has pad tokens after its first real token")
    return pads


def generate(model, input_ids, max_new_tokens: int = 32,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None, seed: int = 0,
             pad_token_id: Optional[int] = None, paged: bool = False,
             block_size: int = 64, num_blocks: Optional[int] = None,
             num_beams: int = 1,
             length_penalty: float = 0.0, repetition_penalty: float = 1.0,
             min_length: int = 0):
    """Decode ``max_new_tokens`` from a Llama- or GPT-family causal
    LM with a KV cache; the whole loop is ONE jitted scan. Returns
    ``[B, prompt_len + max_new_tokens]`` (prompt included); positions
    after an emitted ``eos_token_id`` are filled with eos.

    ``pad_token_id``: enables LEFT-padded mixed-length prompts (each
    row decodes at its own logical positions). ``paged=True`` decodes
    over a paged/block KV cache via the serving ``block_mha_p`` program
    (Llama and GPT families; composes with ragged prompts).
    ``num_blocks`` caps the paged pool size: the call FAILS LOUDLY
    (``ValueError`` naming required vs available blocks) when the
    batch's KV working set cannot fit, instead of clamping the block
    table and silently gathering another row's cache — the
    ``serve.BlockPool`` exhaustion contract applied to the library
    call (``None`` sizes the pool exactly to the batch).
    ``num_beams > 1``: beam search (reference surface:
    nn.BeamSearchDecoder / ecosystem generate), ranked by sum logprob /
    len**``length_penalty`` (0.0 = no length normalization).
    ``repetition_penalty`` (CTRL-style: seen tokens' logits divided by
    the factor when positive, multiplied when negative — prompt tokens
    count as seen) and ``min_length`` (eos masked out for the first
    ``min_length`` new tokens) apply to the greedy/sampling paths."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(np.asarray(input_ids))
    ids = ids.astype(jnp.int32)
    if ids.ndim != 2:
        raise ValueError("generate expects [batch, prompt_len] input_ids")
    b, t0 = ids.shape
    if max_new_tokens <= 0:
        return Tensor._from_value(ids)
    pads_np = None
    if pad_token_id is not None:
        pads_np = _check_left_padded(np.asarray(ids), int(pad_token_id))
        if not pads_np.any():
            pads_np = None                    # no row is actually padded
    if repetition_penalty <= 0.0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}")
    if length_penalty != 0.0 and num_beams <= 1:
        raise ValueError(
            "generate: length_penalty ranks beam-search hypotheses; it "
            "has no effect with num_beams=1 — refusing to silently "
            "ignore it")
    if num_blocks is not None and not paged:
        # checked BEFORE the beam early-return so num_beams>1 cannot
        # silently swallow a num_blocks the caller thought was in force
        raise ValueError(
            "generate: num_blocks sizes the paged KV pool; it has no "
            "effect without paged=True — refusing to silently ignore it")
    if num_beams > 1:
        if do_sample:
            raise ValueError(
                "generate: num_beams > 1 is deterministic beam search; "
                "it does not compose with do_sample")
        if paged or pads_np is not None:
            raise NotImplementedError(
                "generate: beam search runs on the dense same-length "
                "cache path (no paged=True / ragged prompts)")
        if repetition_penalty != 1.0 or min_length:
            raise NotImplementedError(
                "generate: repetition_penalty/min_length apply to the "
                "greedy/sampling paths, not beam search")
        return _generate_beam(model, ids, max_new_tokens=max_new_tokens,
                              num_beams=num_beams,
                              eos_token_id=eos_token_id,
                              length_penalty=length_penalty)
    if paged:
        if repetition_penalty != 1.0 or min_length:
            raise NotImplementedError(
                "generate: repetition_penalty/min_length run on the "
                "dense cache path (no paged=True)")
        return _generate_paged(model, ids, pads_np,
                               max_new_tokens=max_new_tokens,
                               do_sample=do_sample, temperature=temperature,
                               top_k=top_k, top_p=top_p,
                               eos_token_id=eos_token_id, seed=seed,
                               block_size=block_size,
                               num_blocks=num_blocks)
    if min_length > 0 and eos_token_id is None:
        # the beam/paged branches above already reject min_length loudly;
        # on the greedy/sampling path it works by masking eos, so with no
        # eos it would be a silent no-op — refuse instead (the module's
        # no-silently-ignored-arguments posture)
        raise ValueError(
            "generate: min_length works by masking the eos token for the "
            "first min_length new tokens; it has no effect with "
            "eos_token_id=None — refusing to silently ignore it")
    p, fwd = _decode_family(model)
    if pads_np is not None and any("moe" in lp for lp in p["layers"]):
        raise NotImplementedError(
            "generate: ragged (left-padded) prompts are not supported "
            "for MoE models — pad rows would consume expert capacity, "
            "so a padded row could not reproduce its solo decode")
    s_max = t0 + max_new_tokens
    nkv, dh, L = p["nkv"], p["dh"], len(p["layers"])
    dtype = p["embed"].dtype
    eos = -1 if eos_token_id is None else int(eos_token_id)
    static_cfg, arrays, cache = _prep_decode(model, p, t0, max_new_tokens)

    rep = float(repetition_penalty)
    min_new = int(min_length)

    def _run(arrs, ids, pads, key):
        p = {**arrs, **static_cfg}
        vocab = p["embed"].shape[0]

        def penalize(logits, presence, i):
            """CTRL repetition penalty over seen tokens + min-length
            eos mask; identity when both knobs are off (rep==1, the
            common case, compiles to nothing)."""
            if rep != 1.0:
                scaled = jnp.where(logits > 0, logits / rep, logits * rep)
                logits = jnp.where(presence, scaled, logits)
            if min_new > 0 and eos >= 0:
                blocked = jnp.full_like(logits[:, eos], -jnp.inf)
                logits = logits.at[:, eos].set(
                    jnp.where(i < min_new, blocked, logits[:, eos]))
            return logits

        # tokens already in the prompt count as seen (pad runs don't)
        row = jnp.arange(b)[:, None]
        seen_ok = (jnp.ones((b, t0), bool) if pads is None
                   else jnp.arange(t0)[None, :] >= pads[:, None])
        presence0 = jnp.zeros((b, vocab), bool).at[row, ids].max(seen_ok)
        caches = [(jnp.zeros((b, s_max, nkv, dh), dtype),
                   jnp.zeros((b, s_max, nkv, dh), dtype))
                  for _ in range(L)]
        hidden, caches = fwd(p, ids, caches, 0, s_max, pads=pads)
        logits0 = penalize(
            _head_logits(p, hidden).astype(jnp.float32), presence0, 0)
        key, sub = jax.random.split(key)
        tok0 = _sample_token(logits0, sub, do_sample=do_sample,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p)
        done0 = tok0 == eos
        presence0 = presence0.at[jnp.arange(b), tok0].set(True)
        flat_caches = [c for pair in caches for c in pair]

        def step(carry, i):
            # the carried token is the sequence element at absolute
            # position t0 + i - 1: that is its cache slot and its RoPE
            # position (feeding it one slot later leaves the all-zeros
            # slot t0 visible and shifts every rope angle — caught by
            # review, pinned by the multi-token oracle test)
            tok, done, presence, key, *flat = carry
            caches_ = [(flat[2 * j], flat[2 * j + 1]) for j in range(L)]
            hidden, caches_ = fwd(
                p, tok[:, None], caches_, t0 + i - 1, s_max, pads=pads)
            logits = penalize(
                _head_logits(p, hidden).astype(jnp.float32), presence, i)
            key, sub = jax.random.split(key)
            nxt = _sample_token(logits, sub, do_sample=do_sample,
                                temperature=temperature, top_k=top_k,
                                top_p=top_p)
            nxt = jnp.where(done, jnp.int32(eos), nxt)
            done = done | (nxt == eos)
            presence = presence.at[jnp.arange(b), nxt].set(True)
            flat_ = [c for pair in caches_ for c in pair]
            return (nxt, done, presence, key, *flat_), tok

        (last, _done, _pres, _key, *_rest), toks = lax.scan(
            step, (tok0, done0, presence0, key, *flat_caches),
            jnp.arange(1, max_new_tokens))
        toks = jnp.concatenate([toks.swapaxes(0, 1), last[:, None]], axis=1)
        return jnp.concatenate([ids, toks], axis=1)

    # compiled-step cache on the model: params ride as jit ARGUMENTS
    # (weights update between calls; baking them as closure constants
    # would both bloat the executable and force a retrace per call)
    ragged = pads_np is not None
    # dtype is part of the key: _run closes over the cache dtype/layer
    # count captured at first trace — a model.bfloat16() after a float32
    # generate must not reuse the stale closure
    sig = (b, t0, max_new_tokens, do_sample, float(temperature),
           int(top_k), float(top_p), eos, ragged, str(dtype), L,
           rep, min_new, p.get("moe_statics"))
    fn = cache.get(sig)
    if fn is None:
        fn = jax.jit(_run, static_argnums=() if ragged else (2,))
        cache[sig] = fn
    pads_arg = jnp.asarray(pads_np) if ragged else None
    out = fn(arrays, ids, pads_arg, jax.random.PRNGKey(seed))
    return Tensor._from_value(out)


def _generate_beam(model, ids, *, max_new_tokens, num_beams,
                   eos_token_id, length_penalty=0.0):
    """Beam search over the SAME cached single-jit scan as greedy: the
    batch dim carries B*K beam rows, each tick forwards every beam one
    token, expands to K*V candidates, keeps the top K per batch row,
    and reorders the KV caches by each survivor's parent beam. Finished
    beams (emitted eos) are frozen: their only continuation is eos at
    zero added logprob. Returns each row's highest-sum-logprob beam.

    ``length_penalty`` != 0 ranks final beams by
    sum_logprob / len(generated)**length_penalty (GNMT normalization;
    0.0 keeps the raw sum — the oracle-pinned default).

    Reference surface: nn/decode.py BeamSearchDecoder/dynamic_decode is
    the seq2seq cell path; this is the decoder-only LLM analog (the
    reference ecosystem's model.generate(decode_strategy=
    "beam_search"))."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    p, fwd = _decode_family(model)
    b, t0 = ids.shape
    K = int(num_beams)
    s_max = t0 + max_new_tokens
    vocab = p["embed"].shape[0]
    if K > vocab:
        raise ValueError(f"num_beams ({K}) > vocab size ({vocab})")
    nkv, dh, L = p["nkv"], p["dh"], len(p["layers"])
    dtype = p["embed"].dtype
    eos = -1 if eos_token_id is None else int(eos_token_id)
    static_cfg, arrays, cache = _prep_decode(model, p, t0, max_new_tokens)

    def _run(arrs, ids):
        p = {**arrs, **static_cfg}
        # eos-continuation row for finished beams: only eos, at +0
        frozen = jnp.full((vocab,), -jnp.inf)
        if eos >= 0:
            frozen = frozen.at[eos].set(0.0)

        # ---- prefill on the B prompt rows, then expand to K beams ----
        caches = [(jnp.zeros((b, s_max, nkv, dh), dtype),
                   jnp.zeros((b, s_max, nkv, dh), dtype))
                  for _ in range(L)]
        hidden, caches = fwd(p, ids, caches, 0, s_max)
        lp0 = jax.nn.log_softmax(
            _head_logits(p, hidden).astype(jnp.float32), axis=-1)
        scores, tok0 = lax.top_k(lp0, K)               # [B, K] each
        tok0 = tok0.astype(jnp.int32)
        done = tok0 == eos
        gen_len = jnp.ones((b, K), jnp.int32)          # tokens incl. eos
        flat = [jnp.repeat(c, K, axis=0)               # [B*K, S, kvh, dh]
                for pair in caches for c in pair]
        tok_buf = jnp.full((b, K, max_new_tokens), eos, jnp.int32)
        tok_buf = tok_buf.at[:, :, 0].set(tok0)

        def reorder(arr, parent):
            """[B*K, ...] gathered by each survivor's parent beam."""
            v = arr.reshape((b, K) + arr.shape[1:])
            idx = parent.reshape((b, K) + (1,) * (v.ndim - 2))
            return jnp.take_along_axis(v, idx, axis=1).reshape(arr.shape)

        def step(carry, i):
            tok, scores, done, gen_len, tok_buf, *flat = carry
            caches_ = [(flat[2 * j], flat[2 * j + 1]) for j in range(L)]
            hidden, caches_ = fwd(
                p, tok.reshape(b * K, 1), caches_, t0 + i - 1, s_max)
            lp = jax.nn.log_softmax(
                _head_logits(p, hidden).astype(jnp.float32),
                axis=-1).reshape(b, K, vocab)
            lp = jnp.where(done[:, :, None], frozen[None, None, :], lp)
            cand = (scores[:, :, None] + lp).reshape(b, K * vocab)
            scores, idx = lax.top_k(cand, K)           # [B, K]
            parent = (idx // vocab).astype(jnp.int32)
            token = (idx % vocab).astype(jnp.int32)
            flat_ = [reorder(c, parent)
                     for pair in caches_ for c in pair]
            parent_done = jnp.take_along_axis(done, parent, axis=1)
            done = parent_done | (token == eos)
            gen_len = jnp.take_along_axis(gen_len, parent, axis=1) \
                + (~parent_done).astype(jnp.int32)
            tok_buf = jnp.take_along_axis(
                tok_buf, parent[:, :, None], axis=1).at[:, :, i].set(token)
            return (token, scores, done, gen_len, tok_buf, *flat_), ()

        (_tok, scores, _done, gen_len, tok_buf, *_rest), _ = lax.scan(
            step, (tok0, scores, done, gen_len, tok_buf, *flat),
            jnp.arange(1, max_new_tokens))
        if length_penalty != 0.0:
            scores = scores / (gen_len.astype(jnp.float32)
                               ** float(length_penalty))
        best = jnp.argmax(scores, axis=1)              # [B]
        out = jnp.take_along_axis(
            tok_buf, best[:, None, None], axis=1)[:, 0, :]
        return jnp.concatenate([ids, out], axis=1)

    sig = ("beam", b, t0, max_new_tokens, K, eos, str(dtype), L,
           float(length_penalty), p.get("moe_statics"))
    fn = cache.get(sig)
    if fn is None:
        fn = jax.jit(_run)
        cache[sig] = fn
    return Tensor._from_value(fn(arrays, ids))


def generate_speculative(model, draft_model, input_ids,
                         max_new_tokens: int = 32, gamma: int = 4,
                         eos_token_id: Optional[int] = None):
    """Speculative GREEDY decoding: ``draft_model`` proposes ``gamma``
    tokens per round with its own cached scan, the target verifies all
    of them in ONE batched cached forward, and the longest matching
    prefix plus the target's own next token are accepted — so the
    output is EXACTLY ``model``'s greedy decode (the acceptance rule
    only ever keeps tokens the target itself would have emitted), while
    each accepted draft token saves one full target forward.

    The whole loop is one jitted ``lax.while_loop``; cache "rollback"
    after a rejection is free because the dense cache is addressed by
    position — stale slots are simply overwritten before they become
    visible. Batch size 1 (the latency-bound serving regime speculative
    decoding exists for). Reference surface: the ecosystem's
    speculative/draft-model decoding over the same serving cache ops.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(np.asarray(input_ids))
    ids = ids.astype(jnp.int32)
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(
            "generate_speculative expects [1, prompt_len] input_ids "
            "(batch 1 — the latency-bound regime)")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    t0 = ids.shape[1]
    if max_new_tokens <= 0:
        return Tensor._from_value(ids)
    pt, fwd_t = _decode_family(model)
    pd, fwd_d = _decode_family(draft_model)
    if pt.get("moe_statics") or pd.get("moe_statics"):
        raise NotImplementedError(
            "generate_speculative supports dense families only: a MoE "
            "model's expert capacity is computed per call, so the "
            "multi-token verify window could drop tokens that the "
            "one-token-per-step greedy decode keeps, breaking the "
            "exact-equality guarantee")
    if pt["embed"].shape[0] != pd["embed"].shape[0]:
        raise ValueError(
            f"target and draft vocabularies differ "
            f"({pt['embed'].shape[0]} vs {pd['embed'].shape[0]})")
    # buffer leaves room for one full overshoot round past max_new
    cap = max_new_tokens + gamma + 1
    s_max = t0 + cap
    eos = -1 if eos_token_id is None else int(eos_token_id)
    st_t, arr_t, cache = _prep_decode(model, pt, t0, cap)
    st_d, arr_d, _ = _prep_decode(draft_model, pd, t0, cap)
    L_t, L_d = len(pt["layers"]), len(pd["layers"])

    def _mk_caches(p, L):
        return [(jnp.zeros((1, s_max, p["nkv"], p["dh"]),
                           p["embed"].dtype),
                 jnp.zeros((1, s_max, p["nkv"], p["dh"]),
                           p["embed"].dtype)) for _ in range(L)]

    def _run(at, ad, ids):
        pt = {**at, **st_t}
        pd = {**ad, **st_d}

        # prefill BOTH models; target's argmax is the first pending tok
        ct = _mk_caches(pt, L_t)
        cd = _mk_caches(pd, L_d)
        hid, ct = fwd_t(pt, ids, ct, 0, s_max)
        pending = jnp.argmax(_head_logits(pt, hid),
                             axis=-1).astype(jnp.int32)     # [1]
        _hd, cd = fwd_d(pd, ids, cd, 0, s_max)
        out_buf = jnp.full((1, cap), eos if eos >= 0 else 0, jnp.int32)
        flat_t = [c for pair in ct for c in pair]
        flat_d = [c for pair in cd for c in pair]

        def cond(state):
            n_gen = state[0]
            return n_gen < max_new_tokens

        def body(state):
            n_gen, pending, out_buf, *flat = state
            ct_ = [(flat[2 * j], flat[2 * j + 1]) for j in range(L_t)]
            off = 2 * L_t
            cd_ = [(flat[off + 2 * j], flat[off + 2 * j + 1])
                   for j in range(L_d)]
            P = t0 + n_gen                 # pending token's position

            # --- draft phase: gamma greedy tokens from the draft ---
            def dstep(carry, i):
                tok, *dflat = carry
                dc = [(dflat[2 * j], dflat[2 * j + 1])
                      for j in range(L_d)]
                hid, dc = fwd_d(pd, tok[:, None], dc, P + i, s_max)
                nxt = jnp.argmax(_head_logits(pd, hid),
                                 axis=-1).astype(jnp.int32)
                dflat_ = [c for pair in dc for c in pair]
                return (nxt, *dflat_), nxt

            dflat0 = [c for pair in cd_ for c in pair]
            (last_d, *dflat_), drafts = lax.scan(
                dstep, (pending, *dflat0), jnp.arange(gamma))
            drafts = drafts[:, 0]                         # [gamma]
            cd_ = [(dflat_[2 * j], dflat_[2 * j + 1])
                   for j in range(L_d)]
            # forward d_gamma too (logits discarded): a fully-accepted
            # round advances past slot P+gamma, which would otherwise
            # stay an unwritten-but-visible hole in the draft's cache
            # and silently corrupt every later draft proposal
            _hd, cd_ = fwd_d(pd, last_d[:, None], cd_, P + gamma, s_max)

            # --- verify: ONE target forward over pending + drafts ---
            window = jnp.concatenate([pending, drafts])[None, :]
            hid_all, ct_ = fwd_t(pt, window, ct_, P, s_max,
                                 return_all=True)
            t_preds = jnp.argmax(
                _head_logits(pt, hid_all[0]), axis=-1
            ).astype(jnp.int32)                           # [gamma+1]

            # longest matching prefix, then the target's own token:
            # this round emits [pending, d_1..d_a] (a+1 tokens, all of
            # them the target's own greedy choices) and the fix/bonus
            # token y becomes the next pending
            matches = t_preds[:gamma] == drafts
            a = jnp.sum(jnp.cumprod(matches.astype(jnp.int32)))
            y = t_preds[a]
            # the verify window IS the emit candidate list; slots past
            # a+1 hold rejected drafts that the NEXT round overwrites
            # (the loop exits only once n_gen >= max_new, so every slot
            # below max_new ends up final)
            out_buf = lax.dynamic_update_slice(
                out_buf, window, (jnp.int32(0), n_gen))
            n_gen = (n_gen + a + 1).astype(jnp.int32)
            flat_t_ = [c for pair in ct_ for c in pair]
            flat_d_ = [c for pair in cd_ for c in pair]
            return (n_gen, y[None], out_buf, *flat_t_, *flat_d_)

        state = (jnp.int32(0), pending, out_buf, *flat_t, *flat_d)
        state = lax.while_loop(cond, body, state)
        out = state[2][:, :max_new_tokens]
        if eos >= 0:
            # greedy-equivalent eos semantics: everything after the
            # first eos is eos
            seen = jnp.cumsum((out == eos).astype(jnp.int32), axis=1)
            prior = seen - (out == eos).astype(jnp.int32)
            out = jnp.where(prior > 0, jnp.int32(eos), out)
        return jnp.concatenate([ids, out], axis=1)

    # the compiled fn closes over BOTH models' statics only (weights
    # ride as jit arguments), so the key is the statics themselves — a
    # recreated draft with identical architecture reuses the executable,
    # and no stale closure can survive an id() reuse
    sig = ("spec", t0, max_new_tokens, gamma, eos,
           str(pt["embed"].dtype), L_t, str(pd["embed"].dtype), L_d,
           tuple(sorted((k, v) for k, v in st_t.items())),
           tuple(sorted((k, v) for k, v in st_d.items())))
    fn = cache.get(sig)
    if fn is None:
        fn = jax.jit(_run)
        cache[sig] = fn
    out = fn(arr_t, arr_d, ids)
    return Tensor._from_value(out)


def _paged_block_tables(b, s_max, block_size, num_blocks=None):
    """Disjoint row-major block allocation for a ``generate`` batch:
    row ``r`` owns blocks ``[r*blocks_per_seq, (r+1)*blocks_per_seq)``.

    Raises a CLEAR error when a caller-capped pool (``num_blocks``)
    cannot hold the batch's KV working set — the previous behavior was
    an out-of-range block id silently clamped by the gather, reading
    ANOTHER row's cache (ISSUE 14 satellite; regression-tested)."""
    blocks_per_seq = -(-s_max // block_size)
    needed = b * blocks_per_seq
    if num_blocks is not None and int(num_blocks) < needed:
        raise ValueError(
            f"generate(paged=True): KV block pool exhausted before "
            f"decode could start — the batch needs {needed} blocks "
            f"({b} rows x {blocks_per_seq} blocks of {block_size} "
            f"tokens for prompt+max_new_tokens={s_max}) but "
            f"num_blocks={int(num_blocks)}. Grow the pool, shrink the "
            f"batch/max_new_tokens, or serve the requests through "
            f"paddle_tpu.serve.ServeEngine, which queues and preempts "
            f"instead of failing")
    total = needed if num_blocks is None else int(num_blocks)
    tables = (np.arange(needed, dtype=np.int32)
              .reshape(b, blocks_per_seq))
    return tables, total


def _generate_paged(model, ids, pads_np, *, max_new_tokens, do_sample,
                    temperature, top_k, top_p, eos_token_id, seed,
                    block_size, num_blocks=None):
    """Paged/block-KV-cache decode (Llama and GPT families): the
    prefill packs each row's REAL tokens left-aligned into a varlen
    batch and one ``block_mha_p`` call per layer writes them straight
    into the block pool; each scan tick appends one token per row
    through the same program's decode branch. Cache memory is
    per-LOGICAL-token (pads never enter the pool), and the attention
    view is gathered through the block table exactly like the
    reference's serving kernel (block_multihead_attention.py:19). RoPE
    rides inside the block program (Llama); learned positions are added
    at the embedding by logical position (GPT).

    MEASURED (tools/paged_decode_probe.py + paged_kernel_probe.py,
    v5e): the block-table gather/scatter program is ~10x slower than
    the dense scan at 645M serving shapes, and even jax's official
    Pallas paged_attention kernel (numerically equivalent, 1.6x faster
    than the gather) remains ~6x the dense per-layer budget at short
    contexts — paged attention is overhead-bound there. Use paged for
    its cache semantics (ragged pools, pad-free memory, the reference
    serving interface); the dense scan is the throughput path."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..incubate.nn.functional import _rope_tables
    from ..incubate.nn.functional.inference_attention import _bmha_fwd

    if not hasattr(model, "llama") and not hasattr(model, "gpt"):
        raise NotImplementedError(
            "paged=True decode supports the Llama and GPT families; "
            "MoE models use the dense cache path")
    p, _dense_fwd = _decode_family(model)
    is_llama = hasattr(model, "llama")
    b, t0 = ids.shape
    nh, nkv, dh = p["nh"], p["nkv"], p["dh"]
    L = len(p["layers"])
    dtype = p["embed"].dtype
    eos = -1 if eos_token_id is None else int(eos_token_id)
    s_max = t0 + max_new_tokens
    static_cfg, arrays, cache = _prep_decode(model, p, t0, max_new_tokens)
    # loud pool-exhaustion contract (see _paged_block_tables): a capped
    # pool that cannot hold the batch fails HERE, not as a clamped
    # cross-row gather mid-decode
    tables_np, nb = _paged_block_tables(b, s_max, block_size, num_blocks)

    def _run(arrs, ids, pads, key):
        p = {**arrs, **static_cfg}
        tables = jnp.asarray(tables_np)
        enc = (jnp.full((b,), t0, jnp.int32) if pads is None
               else (t0 - pads).astype(jnp.int32))
        # pack real tokens left-aligned per row: row b's segment is
        # [b*t0, b*t0 + enc_b); the clipped tail duplicates are masked
        # out of the cache/attention by enc
        shift = (jnp.zeros((b, 1), jnp.int32) if pads is None
                 else pads[:, None])
        gather_cols = jnp.minimum(shift + jnp.arange(t0)[None, :], t0 - 1)
        packed = jnp.take_along_axis(ids, gather_cols, axis=1).reshape(-1)
        starts = jnp.arange(b, dtype=jnp.int32) * t0
        if is_llama:
            cos_full, sin_full = _rope_tables(s_max, dh, p["theta"], True,
                                              jnp.float32)
            # reference rope layout [2, B, S, 1, D]
            rope = jnp.stack([
                jnp.broadcast_to(cos_full[None, :, None, :],
                                 (b, s_max, 1, dh)),
                jnp.broadcast_to(sin_full[None, :, None, :],
                                 (b, s_max, 1, dh)),
            ]).astype(jnp.float32)
        else:
            rope = jnp.zeros((1,), jnp.float32)   # unused (use_rope=False)
        # packed-token logical positions: left-aligned row segments, so
        # slot j of every segment is position j (prefill); decode steps
        # pass each row's current length instead
        pos_prefill = jnp.tile(jnp.arange(t0, dtype=jnp.int32), b)

        def rms(h, g):
            return _rms(h, g, p["eps"], dtype)

        def ln(h, g, bb):
            return _ln(h, g, bb, p["eps"], dtype)

        def attn(qkv, kc, vc, enc_now, dec_now, cu, win_tables):
            return _bmha_fwd(
                qkv, kc, vc, enc_now, dec_now, cu, win_tables, rope,
                num_heads=nh, kv_num_heads=nkv, block_size=block_size,
                max_seq_len=s_max, use_neox=True, use_rope=is_llama)

        def stack_step(tokens_flat, caches, enc_now, dec_now, cu,
                       pos_tok, win_tables):
            """One forward through all layers on packed rows [T, H];
            returns (hidden rows [T, H], new caches). The norm/FFN math
            is the SHARED per-family helpers (_rms/_ln/_llama_ffn/
            _gpt_ffn) — same source as the dense path, so the two cache
            layouts can't drift."""
            x = jnp.take(p["embed"], tokens_flat, axis=0)
            if not is_llama:
                x = x + jnp.take(p["wpe"], pos_tok, axis=0)
            new_caches = []
            for lp, (kc, vc) in zip(p["layers"], caches):
                if is_llama:
                    h = rms(x, lp["ln1"])
                    qkv = jnp.concatenate(
                        [h @ lp["wq"], h @ lp["wk"], h @ lp["wv"]],
                        axis=-1)
                else:
                    h = ln(x, lp["ln1_w"], lp["ln1_b"])
                    # fused qkv weight is already laid out q|k|v
                    qkv = h @ lp["wqkv"] + lp["bqkv"]
                ctx, _qkv, kc, vc = attn(qkv, kc, vc, enc_now, dec_now,
                                         cu, win_tables)
                new_caches.append((kc, vc))
                if is_llama:
                    x = x + ctx.astype(dtype) @ lp["wo"]
                    x = x + _llama_ffn(rms(x, lp["ln2"]), lp, dtype)
                else:
                    x = x + ctx.astype(dtype) @ lp["wo"] + lp["bo"]
                    x = x + _gpt_ffn(ln(x, lp["ln2_w"], lp["ln2_b"]),
                                     lp, dtype)
            if is_llama:
                return rms(x, p["norm"]), new_caches
            return ln(x, p["normf_w"], p["normf_b"]), new_caches

        caches = [(jnp.zeros((nb, nkv, block_size, dh), dtype),
                   jnp.zeros((nb, nkv, block_size, dh), dtype))
                  for _ in range(L)]
        zeros_b = jnp.zeros((b,), jnp.int32)
        # prefill attends through a PROMPT-SIZED view of the block table:
        # the full table's padded window would cost
        # (ceil(s_max/bs)/ceil(t0/bs))^2 x the live attention FLOPs; the
        # writes land in the same pool either way
        prompt_blocks = -(-t0 // block_size)
        hidden, caches = stack_step(packed, caches, enc, zeros_b, starts,
                                    pos_prefill,
                                    tables[:, :prompt_blocks])
        last_rows = starts + enc - 1
        logits0 = _head_logits(p, hidden[last_rows])
        key, sub = jax.random.split(key)
        tok0 = _sample_token(logits0, sub, do_sample=do_sample,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p)
        done0 = tok0 == eos
        flat = [c for pair in caches for c in pair]
        dec_starts = jnp.arange(b, dtype=jnp.int32)

        def step(carry, i):
            tok, done, key, *flat = carry
            caches_ = [(flat[2 * j], flat[2 * j + 1]) for j in range(L)]
            # the carried token is each row's element at logical
            # position enc + i - 1: its append slot and rope/wpe angle
            hidden, caches_ = stack_step(
                tok, caches_, zeros_b, enc + (i - 1), dec_starts,
                enc + (i - 1), tables)
            logits = _head_logits(p, hidden)
            key, sub = jax.random.split(key)
            nxt = _sample_token(logits, sub, do_sample=do_sample,
                                temperature=temperature, top_k=top_k,
                                top_p=top_p)
            nxt = jnp.where(done, jnp.int32(eos), nxt)
            done = done | (nxt == eos)
            flat_ = [c for pair in caches_ for c in pair]
            return (nxt, done, key, *flat_), tok

        (last, _done, _key, *_rest), toks = lax.scan(
            step, (tok0, done0, key, *flat),
            jnp.arange(1, max_new_tokens))
        toks = jnp.concatenate([toks.swapaxes(0, 1), last[:, None]], axis=1)
        return jnp.concatenate([ids, toks], axis=1)

    ragged = pads_np is not None
    sig = ("paged", b, t0, max_new_tokens, do_sample, float(temperature),
           int(top_k), float(top_p), eos, ragged, int(block_size),
           int(nb), str(dtype), L)
    fn = cache.get(sig)
    if fn is None:
        fn = jax.jit(_run, static_argnums=() if ragged else (2,))
        cache[sig] = fn
    pads_arg = jnp.asarray(pads_np) if ragged else None
    out = fn(arrays, ids, pads_arg, jax.random.PRNGKey(seed))
    return Tensor._from_value(out)
