"""Llama model family — the flagship LLM.

Reference: test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py
(LlamaAttentionAuto :94, LlamaMLPAuto :305, LlamaRMSNorm, LlamaForCausalLMAuto
:809) — the reference's own fixture for exercising dp/mp/pp combos.

TPU design highlights:
- bf16-friendly: params fp32 (or bf16 with master weights), RMSNorm/softmax
  accumulate fp32.
- attention through scaled_dot_product_attention → Pallas flash kernel on
  TPU, XLA composition elsewhere; GQA via num_key_value_heads.
- RoPE via incubate.fused_rotary_position_embedding.
- ``llama_shard_plan(model, mesh)`` applies the Megatron TP layout +
  sequence-parallel activations over a (dp, mp) mesh — matching the
  placements the reference fixture assigns via shard_tensor.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import paddle_tpu as paddle
from .. import nn
from ..core.tensor import Tensor
from ..incubate.nn.functional import fused_rotary_position_embedding, swiglu
from ..nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    use_flash_attention: bool = True
    tie_word_embeddings: bool = False
    recompute: bool = False  # activation checkpointing per decoder layer
    # chunked fused lm_head+cross_entropy: never materializes the fp32
    # [tokens, vocab] logits (the single biggest activation at bs*seq*32k —
    # see incubate/nn/functional/fused_linear_ce.py). Only affects the
    # labels-given training path; generation still returns full logits.
    fused_lm_head_ce: bool = True
    # compute-time q|k|v weight concat: one [h, h+2*kv] projection
    # instead of three narrow ones. Parameters stay SEPARATE (shard
    # plans, checkpoints, parity untouched). MEASURED NULL on the 645M
    # bench geometry (v5e, 2026-07-31): fused 0.676 MFU vs separate
    # 0.697 — XLA already co-schedules same-input matmuls, and the
    # per-step weight concat adds HBM traffic the width-curve gain
    # doesn't repay. Kept as an option for genuinely narrow models;
    # off by default.
    fused_qkv: bool = False
    dtype: str = "float32"
    # context parallelism: "ring" | "ulysses" | None. When set, attention
    # runs over the sequence sharded on cp_mesh_axis (fleet.context_parallel
    # — capability the reference lacks, SURVEY §5.7). Sequences longer than
    # one chip's HBM shard across the sep axis of the active mesh.
    context_parallel: Optional[str] = None
    cp_mesh_axis: str = "sep"

    def __post_init__(self):
        if self.context_parallel not in (None, "ring", "ulysses"):
            raise ValueError(
                f"context_parallel must be None, 'ring' or 'ulysses', "
                f"got {self.context_parallel!r}")

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=8192,
            rope_theta=500000.0,
        )

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
        )
        base.update(kw)
        return LlamaConfig(**base)


class LlamaRMSNorm(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.weight = self.create_parameter(
            [config.hidden_size],
            default_initializer=nn.initializer.Constant(1.0),
        )
        self.eps = config.rms_norm_eps

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.eps)


def fused_qkv_linear(x, projs):
    """One wide GEMM against the CONCATENATED weights of ``projs``
    (nn.Linear layers sharing input ``x``), returning per-proj slices.
    Bias is concatenated when every proj has one. Parameters stay
    separate tensors — this is a compute-time fusion only (see
    LlamaConfig.fused_qkv for the measured effect)."""
    from ..ops.manipulation import concat

    w = concat([p.weight for p in projs], axis=1)
    biases = [getattr(p, "bias", None) for p in projs]
    if all(bb is not None for bb in biases):
        b = concat(biases, axis=0)
    elif any(bb is not None for bb in biases):
        raise ValueError(
            "fused_qkv_linear: projections mix bias and bias-free "
            "layers; fuse only uniform projections (or disable "
            "fused_qkv for this model)")
    else:
        b = None
    out = F.linear(x, w, b)
    widths = [p.weight.shape[1] for p in projs]
    slices, off = [], 0
    for wd in widths:
        slices.append(out[..., off:off + wd])
        off += wd
    return slices


class LlamaAttention(nn.Layer):
    """GQA attention (reference fixture LlamaAttentionAuto:94)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        kv = self.num_kv_heads * self.head_dim
        self.q_proj = nn.Linear(h, h, bias_attr=False)
        self.k_proj = nn.Linear(h, kv, bias_attr=False)
        self.v_proj = nn.Linear(h, kv, bias_attr=False)
        self.o_proj = nn.Linear(h, h, bias_attr=False)

    def forward(self, hidden_states, position_ids=None, attention_mask=None):
        b, s, h = hidden_states.shape
        if self.config.fused_qkv:
            q, k, v = fused_qkv_linear(
                hidden_states, (self.q_proj, self.k_proj, self.v_proj))
            q = q.reshape([b, s, self.num_heads, self.head_dim])
            k = k.reshape([b, s, self.num_kv_heads, self.head_dim])
            v = v.reshape([b, s, self.num_kv_heads, self.head_dim])
        else:
            q = self.q_proj(hidden_states).reshape(
                [b, s, self.num_heads, self.head_dim])
            k = self.k_proj(hidden_states).reshape(
                [b, s, self.num_kv_heads, self.head_dim])
            v = self.v_proj(hidden_states).reshape(
                [b, s, self.num_kv_heads, self.head_dim])
        q, k, v = fused_rotary_position_embedding(
            q, k, v, position_ids=position_ids,
            use_neox_rotary_style=True, rotary_emb_base=self.config.rope_theta,
        )
        if self.config.context_parallel:
            if attention_mask is not None:
                raise NotImplementedError(
                    "context_parallel attention is causal-only; custom "
                    "attention_mask is not supported under ring/ulysses")
            out = self._cp_attention(q, k, v)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attention_mask,
                is_causal=attention_mask is None,
            )
        return self.o_proj(out.reshape([b, s, h]))

    def _cp_attention(self, q, k, v):
        """Ring/Ulysses attention over the sequence-sharded sep axis."""
        from ..distributed.fleet.context_parallel import (
            ring_attention, ulysses_attention,
        )
        from ..ops.manipulation import repeat_interleave

        if self.num_kv_heads != self.num_heads:  # GQA: expand kv heads
            rep = self.num_heads // self.num_kv_heads
            k = repeat_interleave(k, rep, axis=2)
            v = repeat_interleave(v, rep, axis=2)
        fn = {"ring": ring_attention, "ulysses": ulysses_attention}[
            self.config.context_parallel]
        return fn(q, k, v, axis=self.config.cp_mesh_axis, causal=True)


class LlamaMLP(nn.Layer):
    """SwiGLU MLP (reference fixture LlamaMLPAuto:305)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_proj = nn.Linear(config.hidden_size, config.intermediate_size,
                                   bias_attr=False)
        self.up_proj = nn.Linear(config.hidden_size, config.intermediate_size,
                                 bias_attr=False)
        self.down_proj = nn.Linear(config.intermediate_size, config.hidden_size,
                                   bias_attr=False)

    def forward(self, x):
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, position_ids=None, attention_mask=None):
        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        hidden_states = self.self_attn(hidden_states, position_ids, attention_mask)
        hidden_states = residual + hidden_states
        residual = hidden_states
        hidden_states = self.post_attention_layernorm(hidden_states)
        hidden_states = self.mlp(hidden_states)
        return residual + hidden_states


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm = LlamaRMSNorm(config)

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        hidden_states = self.embed_tokens(input_ids)
        if self.config.recompute:
            from ..distributed.fleet.utils import recompute

            for layer in self.layers:
                hidden_states = recompute(
                    layer, hidden_states, position_ids, attention_mask
                )
        else:
            for layer in self.layers:
                hidden_states = layer(hidden_states, position_ids, attention_mask)
        return self.norm(hidden_states)


class LlamaForCausalLM(nn.Layer):
    """Reference fixture LlamaForCausalLMAuto:809."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden_states = self.llama(input_ids, position_ids, attention_mask)
        if labels is not None and self.config.fused_lm_head_ce:
            from ..incubate.nn.functional.fused_linear_ce import (
                fused_linear_cross_entropy,
            )

            loss = fused_linear_cross_entropy(
                hidden_states.reshape([-1, self.config.hidden_size]),
                self.lm_head.weight,
                labels.reshape([-1]),
                ignore_index=-100,
            )
            return loss, None
        logits = self.lm_head(hidden_states)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id=None, seed: int = 0, pad_token_id=None,
                 paged: bool = False, block_size: int = 64,
                 num_blocks=None,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 repetition_penalty: float = 1.0, min_length: int = 0):
        """KV-cache incremental decoding: the whole loop is one jitted
        lax.scan (models/generation.py). Greedy by default; sampling
        via do_sample + temperature/top_k/top_p; ``pad_token_id``
        enables left-padded ragged prompts; ``paged=True`` decodes over
        the serving block/paged KV cache (``num_blocks`` caps the pool
        and fails loudly on exhaustion). Returns
        [B, prompt + max_new_tokens] including the prompt."""
        from .generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         do_sample=do_sample, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         pad_token_id=pad_token_id, paged=paged,
                         block_size=block_size, num_blocks=num_blocks,
                         num_beams=num_beams,
                         length_penalty=length_penalty,
                         repetition_penalty=repetition_penalty,
                         min_length=min_length)


# ---------------------------------------------------------------------------
# Sharding plan — the semi-auto placements the reference fixture assigns
# (semi_auto_parallel_llama_model.py shard_tensor calls), expressed once.
# ---------------------------------------------------------------------------
def llama_shard_plan(model: LlamaForCausalLM, mesh, dp_axis="dp", mp_axis="mp"):
    """Apply Megatron TP + replicated-DP layout over ``mesh``:

    - embed_tokens.weight:    Shard(0) on mp (vocab parallel)
    - q/k/v/gate/up:          Shard(1) on mp (column parallel)
    - o_proj/down_proj:       Shard(0) on mp (row parallel)
    - lm_head.weight:         Shard(1) on mp
    - norms:                  replicated
    """
    import paddle_tpu.distributed as dist

    mp = mesh.dim_names.index(mp_axis)

    def place(p, tensor_dim=None):
        placements = [dist.Replicate() for _ in range(mesh.ndim)]
        if tensor_dim is not None:
            placements[mp] = dist.Shard(tensor_dim)
        dist.shard_tensor(p, mesh, placements)

    place(model.llama.embed_tokens.weight, 0)
    for layer in model.llama.layers:
        place(layer.self_attn.q_proj.weight, 1)
        place(layer.self_attn.k_proj.weight, 1)
        place(layer.self_attn.v_proj.weight, 1)
        place(layer.self_attn.o_proj.weight, 0)
        place(layer.mlp.gate_proj.weight, 1)
        place(layer.mlp.up_proj.weight, 1)
        place(layer.mlp.down_proj.weight, 0)
        place(layer.input_layernorm.weight)
        place(layer.post_attention_layernorm.weight)
    place(model.llama.norm.weight)
    place(model.lm_head.weight, 1)
    return model
