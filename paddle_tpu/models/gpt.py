"""GPT model family (GPT-2/3 style decoder).

Reference: the reference's GPT workloads run through
fleet/meta_parallel + the fused_multi_transformer big-op
(fluid/operators/fused/fused_multi_transformer_op.cu); the architecture
here is the standard pre-LN causal decoder with learned positions, laid
out for the MXU (attention via scaled_dot_product_attention → Pallas
flash on TPU) with XLA doing the fused_multi_transformer-style fusion.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTDecoderLayer",
           "gpt_shard_plan"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    # GPT-2's attn_pdrop; runs inside the Pallas flash kernel (causal +
    # dropout compose in-kernel, ops/pallas/flash_attention.py)
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    tie_word_embeddings: bool = True
    recompute: bool = False

    @staticmethod
    def gpt2() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def gpt2_medium() -> "GPTConfig":
        return GPTConfig(hidden_size=1024, num_hidden_layers=24,
                         num_attention_heads=16, intermediate_size=4096)

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64)
        base.update(kw)
        return GPTConfig(**base)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.config.attention_probs_dropout_prob,
            is_causal=True, training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN block: x + attn(ln(x)); x + mlp(ln(x))."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.norm1 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.norm2 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.linear1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.linear2 = nn.Linear(config.intermediate_size, config.hidden_size)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        return x + self.dropout(self.linear2(F.gelu(self.linear1(self.norm2(x)))))


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm_f = nn.LayerNorm(config.hidden_size,
                                   epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None):
        import paddle_tpu as paddle

        b, s = input_ids.shape
        if position_ids is None:
            position_ids = paddle.arange(s, dtype="int64").unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(position_ids))
        if self.config.recompute:
            from ..distributed.fleet.utils import recompute

            for layer in self.layers:
                x = recompute(layer, x)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.norm_f(x)

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id=None, seed: int = 0, pad_token_id=None,
                 paged: bool = False, block_size: int = 64,
                 num_blocks=None,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 repetition_penalty: float = 1.0, min_length: int = 0):
        """KV-cache incremental decoding — one jitted lax.scan over a
        dense cache (models/generation.py, same driver as Llama);
        ``pad_token_id`` enables left-padded ragged prompts;
        ``paged=True``/``num_blocks`` as in the Llama family."""
        from .generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         do_sample=do_sample, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         pad_token_id=pad_token_id, paged=paged,
                         block_size=block_size, num_blocks=num_blocks,
                         num_beams=num_beams,
                         length_penalty=length_penalty,
                         repetition_penalty=repetition_penalty,
                         min_length=min_length)

    def forward(self, input_ids, position_ids=None, labels=None):
        import paddle_tpu as paddle

        hidden = self.gpt(input_ids, position_ids)
        if self.config.tie_word_embeddings:
            logits = paddle.matmul(hidden, self.gpt.wte.weight,
                                   transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), ignore_index=-100)
            return loss, logits
        return logits

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


def gpt_shard_plan(model: GPTForCausalLM, mesh, dp_axis="dp", mp_axis="mp"):
    """Megatron TP layout: qkv/linear1 column-parallel, out/linear2
    row-parallel, token embeddings vocab-parallel."""
    import paddle_tpu.distributed as dist

    mp = mesh.dim_names.index(mp_axis)

    def place(p, tensor_dim=None):
        placements = [dist.Replicate() for _ in range(mesh.ndim)]
        if tensor_dim is not None:
            placements[mp] = dist.Shard(tensor_dim)
        dist.shard_tensor(p, mesh, placements)

    place(model.gpt.wte.weight, 0)
    for layer in model.gpt.layers:
        place(layer.attn.qkv_proj.weight, 1)
        place(layer.attn.qkv_proj.bias, 0)
        place(layer.attn.out_proj.weight, 0)
        place(layer.linear1.weight, 1)
        place(layer.linear1.bias, 0)
        place(layer.linear2.weight, 0)
    if not model.config.tie_word_embeddings:
        place(model.lm_head.weight, 1)
    return model
