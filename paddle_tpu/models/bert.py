"""BERT model family.

Reference: the reference framework's transformer encoder stack
(python/paddle/nn/layer/transformer.py TransformerEncoder/
TransformerEncoderLayer) as used by ERNIE/BERT workloads, plus the
fused_attention/fused_feedforward big-op pattern
(fluid/operators/fused/fused_attention_op.cu — here XLA fuses the same
graph; SURVEY §2.1 fused-op row).

TPU notes: post-LN encoder, GELU FFN, attention via
scaled_dot_product_attention (Pallas flash on TPU). bert_shard_plan gives
the Megatron TP layout over a (dp, mp) mesh.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F

__all__ = [
    "BertConfig", "BertModel", "BertForPretraining",
    "BertForSequenceClassification", "BertEmbeddings", "BertEncoderLayer",
    "bert_shard_plan",
]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    recompute: bool = False
    # compute-time q|k|v weight concat (one W=3h GEMM instead of three
    # W=h); parameters stay separate (see models/llama.py fused_qkv).
    # MEASURED (v5e bench geometry, 2026-07-31): 0.3884 MFU fused vs
    # 0.3868 separate — within noise; XLA's same-input multi-GEMM
    # scheduling already captures the width win. Off by default.
    fused_qkv: bool = False

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def large() -> "BertConfig":
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, intermediate_size=4096)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64)
        base.update(kw)
        return BertConfig(**base)


class BertEmbeddings(nn.Layer):
    """word + position + token_type embeddings → LayerNorm → dropout."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(
            config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(
            config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as paddle

        b, s = input_ids.shape
        if position_ids is None:
            position_ids = paddle.arange(s, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = paddle.zeros([b, s], dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.q_proj = nn.Linear(h, h)
        self.k_proj = nn.Linear(h, h)
        self.v_proj = nn.Linear(h, h)
        self.out_proj = nn.Linear(h, h)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        b, s, h = x.shape
        if self.config.fused_qkv:
            from .llama import fused_qkv_linear

            q, k, v = fused_qkv_linear(
                x, (self.q_proj, self.k_proj, self.v_proj))
            q = q.reshape([b, s, self.num_heads, self.head_dim])
            k = k.reshape([b, s, self.num_heads, self.head_dim])
            v = v.reshape([b, s, self.num_heads, self.head_dim])
        else:
            q = self.q_proj(x).reshape(
                [b, s, self.num_heads, self.head_dim])
            k = self.k_proj(x).reshape(
                [b, s, self.num_heads, self.head_dim])
            v = self.v_proj(x).reshape(
                [b, s, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            dropout_p=self.config.attention_probs_dropout_prob,
            is_causal=False, training=self.training)
        return self.dropout(self.out_proj(out.reshape([b, s, h])))


class BertEncoderLayer(nn.Layer):
    """Post-LN encoder block (the fused_attention+fused_feedforward graph
    of the reference, left to XLA to fuse)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.self_attn = BertSelfAttention(config)
        self.norm1 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.linear1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.linear2 = nn.Linear(config.intermediate_size, config.hidden_size)
        self.norm2 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        x = self.norm1(x + self.self_attn(x, attention_mask))
        ff = self.linear2(F.gelu(self.linear1(x)))
        return self.norm2(x + self.dropout(ff))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertEncoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [b, s] padding mask → additive [b, 1, 1, s]
            import paddle_tpu as paddle

            neg = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = neg.unsqueeze(1).unsqueeze(1)
        if self.config.recompute:
            from ..distributed.fleet.utils import recompute

            for layer in self.encoder:
                x = recompute(layer, x, attention_mask)
        else:
            for layer in self.encoder:
                x = layer(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class BertForPretraining(nn.Layer):
    """MLM + NSP heads."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.mlm_head = nn.Linear(config.hidden_size, config.vocab_size)
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        mlm = self.mlm_head(self.mlm_norm(F.gelu(self.mlm_transform(seq))))
        nsp = self.nsp_head(pooled)
        if masked_lm_labels is not None:
            loss = F.cross_entropy(
                mlm.reshape([-1, self.config.vocab_size]),
                masked_lm_labels.reshape([-1]), ignore_index=-100)
            if next_sentence_labels is not None:
                loss = loss + F.cross_entropy(
                    nsp, next_sentence_labels.reshape([-1]))
            return loss, mlm, nsp
        return mlm, nsp

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels.reshape([-1])), logits
        return logits


def bert_shard_plan(model, mesh, dp_axis="dp", mp_axis="mp"):
    """Megatron TP layout: qkv/linear1 column-parallel, out/linear2
    row-parallel, word embeddings vocab-parallel."""
    import paddle_tpu.distributed as dist

    mp = mesh.dim_names.index(mp_axis)

    def place(p, tensor_dim=None):
        placements = [dist.Replicate() for _ in range(mesh.ndim)]
        if tensor_dim is not None:
            placements[mp] = dist.Shard(tensor_dim)
        dist.shard_tensor(p, mesh, placements)

    bert = model.bert if hasattr(model, "bert") else model
    place(bert.embeddings.word_embeddings.weight, 0)
    for layer in bert.encoder:
        place(layer.self_attn.q_proj.weight, 1)
        place(layer.self_attn.k_proj.weight, 1)
        place(layer.self_attn.v_proj.weight, 1)
        place(layer.self_attn.out_proj.weight, 0)
        place(layer.linear1.weight, 1)
        place(layer.linear1.bias, 0)
        place(layer.linear2.weight, 0)
    return model
