"""Latent-diffusion UNet (Stable-Diffusion-style conv + GroupNorm +
self/cross-attention).

SURVEY §7 step 12 names this configuration (conv + GroupNorm + cross-attn)
as the compiler-parity workload; the reference ships the equivalent blocks
as fused GPU ops (fluid/operators/fused/fused_gate_attention, paddle vision
conv stacks). Here the UNet composes framework layers so the whole denoise
step compiles to one XLA program — GroupNorm/attention fuse into the conv
pipeline — and the attention path rides the same
scaled_dot_product_attention that dispatches to the Pallas flash kernel on
TPU for flash-compatible shapes.

Layout: NCHW at the module surface (paddle convention).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer
from .. import nn

__all__ = ["UNetConfig", "UNet2DConditionModel", "DDPMScheduler"]


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    sample_size: int = 32
    block_out_channels: Tuple[int, ...] = (128, 256, 512)
    layers_per_block: int = 2
    attention_levels: Tuple[bool, ...] = (False, True, True)
    num_attention_heads: int = 8
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    time_embed_mult: int = 4

    @staticmethod
    def tiny(**kw):
        base = dict(
            in_channels=4, out_channels=4, sample_size=8,
            block_out_channels=(32, 64), layers_per_block=1,
            attention_levels=(False, True), num_attention_heads=4,
            cross_attention_dim=32, norm_num_groups=8,
        )
        base.update(kw)
        return UNetConfig(**base)


def timestep_embedding(timesteps: Tensor, dim: int) -> Tensor:
    """Sinusoidal timestep embedding (DDPM §3.3 convention)."""
    import paddle_tpu as paddle

    half = dim // 2
    freqs = paddle.exp(
        paddle.arange(0, half, dtype="float32") * (-math.log(10000.0) / half)
    )
    args = paddle.cast(timesteps, "float32").unsqueeze(-1) * freqs.unsqueeze(0)
    return paddle.concat([paddle.cos(args), paddle.sin(args)], axis=-1)


class ResnetBlock2D(Layer):
    def __init__(self, in_ch, out_ch, temb_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(groups, out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.act = nn.SiLU()
        self.shortcut = (
            nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch else None
        )

    def forward(self, x, temb):
        h = self.conv1(self.act(self.norm1(x)))
        h = h + self.time_emb_proj(self.act(temb)).unsqueeze(-1).unsqueeze(-1)
        h = self.conv2(self.act(self.norm2(h)))
        if self.shortcut is not None:
            x = self.shortcut(x)
        return x + h


class _Attention(Layer):
    """Multi-head attention over flattened spatial tokens; context=None →
    self-attention. Runs through scaled_dot_product_attention (Pallas flash
    on TPU when shapes align)."""

    def __init__(self, query_dim, context_dim, heads):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(query_dim, query_dim, bias_attr=False)
        self.to_k = nn.Linear(context_dim, query_dim, bias_attr=False)
        self.to_v = nn.Linear(context_dim, query_dim, bias_attr=False)
        self.to_out = nn.Linear(query_dim, query_dim)

    def forward(self, x, context=None):
        ctx = x if context is None else context
        b, n, c = x.shape
        h = self.heads
        q = self.to_q(x).reshape([b, n, h, c // h])
        k = self.to_k(ctx).reshape([b, ctx.shape[1], h, c // h])
        v = self.to_v(ctx).reshape([b, ctx.shape[1], h, c // h])
        out = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        return self.to_out(out.reshape([b, n, c]))


class TransformerBlock2D(Layer):
    """norm → self-attn → cross-attn → geglu FFN over spatial tokens."""

    def __init__(self, channels, heads, context_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels)
        self.proj_in = nn.Linear(channels, channels)
        self.norm1 = nn.LayerNorm(channels)
        self.attn1 = _Attention(channels, channels, heads)
        self.norm2 = nn.LayerNorm(channels)
        self.attn2 = _Attention(channels, context_dim, heads)
        self.norm3 = nn.LayerNorm(channels)
        self.ff1 = nn.Linear(channels, channels * 4)
        self.ff2 = nn.Linear(channels * 4, channels)
        self.proj_out = nn.Linear(channels, channels)

    def forward(self, x, context):
        b, c, hh, ww = x.shape
        residual = x
        h = self.norm(x)
        h = h.reshape([b, c, hh * ww]).transpose([0, 2, 1])  # (B, HW, C)
        h = self.proj_in(h)
        h = h + self.attn1(self.norm1(h))
        h = h + self.attn2(self.norm2(h), context)
        h = h + self.ff2(F.gelu(self.ff1(self.norm3(h))))
        h = self.proj_out(h)
        h = h.transpose([0, 2, 1]).reshape([b, c, hh, ww])
        return h + residual


class Downsample2D(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample2D(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        x = F.interpolate(x, scale_factor=2.0, mode="nearest")
        return self.conv(x)


class UNet2DConditionModel(Layer):
    """Conditional denoising UNet: eps = f(latents, t, encoder_hidden_states)."""

    def __init__(self, config: UNetConfig):
        super().__init__()
        self.config = config
        chs = config.block_out_channels
        temb_ch = chs[0] * config.time_embed_mult
        g = config.norm_num_groups

        self.time_mlp1 = nn.Linear(chs[0], temb_ch)
        self.time_mlp2 = nn.Linear(temb_ch, temb_ch)
        self.conv_in = nn.Conv2D(config.in_channels, chs[0], 3, padding=1)

        # down path
        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamplers = nn.LayerList()
        skip_chs = [chs[0]]
        in_ch = chs[0]
        for level, out_ch in enumerate(chs):
            for _ in range(config.layers_per_block):
                self.down_blocks.append(ResnetBlock2D(in_ch, out_ch, temb_ch, g))
                self.down_attns.append(
                    TransformerBlock2D(out_ch, config.num_attention_heads,
                                       config.cross_attention_dim, g)
                    if config.attention_levels[level] else None
                )
                in_ch = out_ch
                skip_chs.append(in_ch)
            if level < len(chs) - 1:
                self.downsamplers.append(Downsample2D(in_ch))
                skip_chs.append(in_ch)
            else:
                self.downsamplers.append(None)

        # middle
        self.mid_block1 = ResnetBlock2D(in_ch, in_ch, temb_ch, g)
        self.mid_attn = TransformerBlock2D(
            in_ch, config.num_attention_heads, config.cross_attention_dim, g
        )
        self.mid_block2 = ResnetBlock2D(in_ch, in_ch, temb_ch, g)

        # up path (mirror with skip concat)
        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamplers = nn.LayerList()
        for level, out_ch in reversed(list(enumerate(chs))):
            for _ in range(config.layers_per_block + 1):
                skip = skip_chs.pop()
                self.up_blocks.append(
                    ResnetBlock2D(in_ch + skip, out_ch, temb_ch, g)
                )
                self.up_attns.append(
                    TransformerBlock2D(out_ch, config.num_attention_heads,
                                       config.cross_attention_dim, g)
                    if config.attention_levels[level] else None
                )
                in_ch = out_ch
            if level > 0:
                self.upsamplers.append(Upsample2D(in_ch))
            else:
                self.upsamplers.append(None)

        self.norm_out = nn.GroupNorm(g, chs[0])
        self.conv_out = nn.Conv2D(chs[0], config.out_channels, 3, padding=1)
        self.act = nn.SiLU()

    def forward(self, sample, timesteps, encoder_hidden_states):
        import paddle_tpu as paddle

        cfg = self.config
        temb = timestep_embedding(timesteps, cfg.block_out_channels[0])
        # the sinusoid is computed in f32; follow the model's compute
        # dtype (bf16 under model.bfloat16()) before it meets the convs
        temb = temb.astype(self.time_mlp1.weight.dtype)
        temb = self.time_mlp2(self.act(self.time_mlp1(temb)))

        h = self.conv_in(sample)
        skips = [h]
        i = 0
        for level in range(len(cfg.block_out_channels)):
            for _ in range(cfg.layers_per_block):
                h = self.down_blocks[i](h, temb)
                if self.down_attns[i] is not None:
                    h = self.down_attns[i](h, encoder_hidden_states)
                skips.append(h)
                i += 1
            if self.downsamplers[level] is not None:
                h = self.downsamplers[level](h)
                skips.append(h)

        h = self.mid_block1(h, temb)
        h = self.mid_attn(h, encoder_hidden_states)
        h = self.mid_block2(h, temb)

        i = 0
        for idx, level in enumerate(reversed(range(len(cfg.block_out_channels)))):
            for _ in range(cfg.layers_per_block + 1):
                h = paddle.concat([h, skips.pop()], axis=1)
                h = self.up_blocks[i](h, temb)
                if self.up_attns[i] is not None:
                    h = self.up_attns[i](h, encoder_hidden_states)
                i += 1
            if self.upsamplers[idx] is not None:
                h = self.upsamplers[idx](h)

        return self.conv_out(self.act(self.norm_out(h)))

    def num_parameters(self):
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class DDPMScheduler:
    """Minimal DDPM noise schedule (linear betas): add_noise for training,
    step() for ancestral sampling."""

    def __init__(self, num_train_timesteps=1000, beta_start=1e-4, beta_end=0.02):
        self.num_train_timesteps = num_train_timesteps
        betas = np.linspace(beta_start, beta_end, num_train_timesteps,
                            dtype="float64")
        alphas_cumprod = np.cumprod(1.0 - betas)
        self._betas = betas.astype("float32")
        self._alphas_cumprod = alphas_cumprod.astype("float32")
        self._ac_tensor = None

    def add_noise(self, clean, noise, timesteps):
        import paddle_tpu as paddle

        if self._ac_tensor is None:
            # one-time device upload of the schedule table
            self._ac_tensor = paddle.to_tensor(self._alphas_cumprod)
        a = paddle.gather(self._ac_tensor, timesteps).reshape([-1, 1, 1, 1])
        return paddle.sqrt(a) * clean + paddle.sqrt(1.0 - a) * noise

    def step(self, eps_pred, t: int, sample, key_noise=None):
        import paddle_tpu as paddle

        beta = float(self._betas[t])
        alpha = 1.0 - beta
        ac = float(self._alphas_cumprod[t])
        coef = beta / math.sqrt(1.0 - ac)
        mean = (sample - coef * eps_pred) / math.sqrt(alpha)
        if t == 0:
            return mean
        noise = key_noise if key_noise is not None else paddle.randn(
            sample.shape, dtype=str(np.dtype(sample.dtype))
        )
        return mean + math.sqrt(beta) * noise
