"""ERNIE-4.5-MoE-style mixture-of-experts causal LM.

BASELINE.json config 4 ("ERNIE-4.5 MoE — expert-parallel all_to_all over
ICI, fused_moe kernel"). The decoder reuses the Llama attention/RMSNorm
blocks; FFNs alternate between a dense MLP and a FusedMoELayer whose
routing dispatch is the einsum the EP sharding turns into the all-to-all
(incubate/distributed/models/moe). The training loss adds the gates'
load-balancing aux loss, and ``ernie_moe_shard_plan`` lays out Megatron TP
for attention + expert-dim sharding for the expert banks over a dp×mp×ep
mesh.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import nn
from ..nn import functional as F
from ..incubate.distributed.models.moe import FusedMoELayer
from .llama import LlamaAttention, LlamaConfig, LlamaMLP, LlamaRMSNorm

__all__ = ["ErnieMoeConfig", "ErnieMoeForCausalLM", "ErnieMoeModel",
           "ernie_moe_shard_plan"]


@dataclass
class ErnieMoeConfig(LlamaConfig):
    num_experts: int = 8
    moe_top_k: int = 2
    moe_layer_interval: int = 2      # every k-th decoder layer is MoE
    moe_intermediate_size: Optional[int] = None
    aux_loss_weight: float = 0.01
    gate_type: str = "gshard"
    # "swiglu" = ERNIE-4.5's expert form with gate+up CONCATENATED into
    # one [d, 2H] projection (one wide GEMM instead of two narrow ones —
    # see ExpertsFFN); "gelu" keeps the classic 2-GEMM FFN expert
    moe_activation: str = "gelu"

    @staticmethod
    def tiny(**kw) -> "ErnieMoeConfig":
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            num_experts=4, moe_top_k=2, moe_layer_interval=1,
        )
        base.update(kw)
        return ErnieMoeConfig(**base)

    def is_moe_layer(self, idx: int) -> bool:
        return (idx + 1) % self.moe_layer_interval == 0


class ErnieMoeDecoderLayer(nn.Layer):
    def __init__(self, config: ErnieMoeConfig, layer_idx: int,
                 moe_group=None):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)
        self.is_moe = config.is_moe_layer(layer_idx)
        if self.is_moe:
            self.mlp = FusedMoELayer(
                config.hidden_size,
                config.moe_intermediate_size or config.intermediate_size,
                config.num_experts,
                gate={"type": config.gate_type, "topk": config.moe_top_k},
                activation=config.moe_activation,
                moe_group=moe_group,
            )
        else:
            self.mlp = LlamaMLP(config)

    def forward(self, hidden_states, position_ids=None, attention_mask=None):
        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        hidden_states = self.self_attn(hidden_states, position_ids, attention_mask)
        hidden_states = residual + hidden_states
        residual = hidden_states
        hidden_states = self.post_attention_layernorm(hidden_states)
        hidden_states = self.mlp(hidden_states)
        return residual + hidden_states


class ErnieMoeModel(nn.Layer):
    def __init__(self, config: ErnieMoeConfig, moe_group=None):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([
            ErnieMoeDecoderLayer(config, i, moe_group=moe_group)
            for i in range(config.num_hidden_layers)
        ])
        self.norm = LlamaRMSNorm(config)

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        hidden_states = self.embed_tokens(input_ids)
        if self.config.recompute:
            from ..distributed.fleet.utils import recompute

            for layer in self.layers:
                if layer.is_moe:
                    # MoE layers run un-checkpointed: recompute's no_grad
                    # forward would detach the gate's load-balancing aux
                    # loss, silently un-training the router
                    hidden_states = layer(
                        hidden_states, position_ids, attention_mask
                    )
                else:
                    hidden_states = recompute(
                        layer, hidden_states, position_ids, attention_mask
                    )
        else:
            for layer in self.layers:
                hidden_states = layer(hidden_states, position_ids, attention_mask)
        return self.norm(hidden_states)


class ErnieMoeForCausalLM(nn.Layer):
    def __init__(self, config: ErnieMoeConfig, moe_group=None):
        super().__init__()
        self.config = config
        self.model = ErnieMoeModel(config, moe_group=moe_group)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)

    def moe_aux_loss(self):
        """Sum the gates' pending load-balancing losses (clears them)."""
        total = None
        for layer in self.model.layers:
            gate = getattr(layer.mlp, "gate", None)
            if gate is not None and hasattr(gate, "get_loss"):
                l = gate.get_loss(clear=True)
                if l is not None:
                    total = l if total is None else total + l
        return total

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden_states = self.model(input_ids, position_ids, attention_mask)
        logits = self.lm_head(hidden_states)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]),
                ignore_index=-100,
            )
            aux = self.moe_aux_loss()
            if aux is not None:
                loss = loss + self.config.aux_loss_weight * aux
            return loss, logits
        return logits

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id=None, seed: int = 0,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 repetition_penalty: float = 1.0, min_length: int = 0):
        """KV-cache incremental decoding for the MoE family — the same
        single-jit scan as Llama (models/generation.py) with the
        routed-expert FFN run per step through the index-dispatch
        program (EVAL routing: deterministic top-k, eval capacity)."""
        from .generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         do_sample=do_sample, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         num_beams=num_beams,
                         length_penalty=length_penalty,
                         repetition_penalty=repetition_penalty,
                         min_length=min_length)


def ernie_moe_shard_plan(model: ErnieMoeForCausalLM, mesh, mp_axis="mp",
                         ep_axis="ep"):
    """mp×ep layout: Megatron TP on attention/dense-MLP/vocab (when
    ``mp_axis`` exists in the mesh), expert-dim sharding on the fused expert
    banks (GSPMD turns the routing einsums into the all_to_all the reference
    issues via global_scatter/global_gather). Data parallelism needs no
    parameter placement — it comes from sharding the batch inputs."""
    import paddle_tpu.distributed as dist

    mp = mesh.dim_names.index(mp_axis) if mp_axis in mesh.dim_names else None
    ep = mesh.dim_names.index(ep_axis) if ep_axis in mesh.dim_names else None

    def place(p, dim=None, axis_idx=None):
        placements = [dist.Replicate() for _ in range(mesh.ndim)]
        target = mp if axis_idx is None else axis_idx
        if dim is not None and target is not None:
            placements[target] = dist.Shard(dim)
        dist.shard_tensor(p, mesh, placements)

    place(model.model.embed_tokens.weight, 0)
    place(model.lm_head.weight, 1)
    for layer in model.model.layers:
        place(layer.self_attn.q_proj.weight, 1)
        place(layer.self_attn.k_proj.weight, 1)
        place(layer.self_attn.v_proj.weight, 1)
        place(layer.self_attn.o_proj.weight, 0)
        if layer.is_moe:
            experts = layer.mlp.experts
            for w in (experts.w0, experts.b0, experts.w1, experts.b1):
                if ep is not None:
                    place(w, 0, axis_idx=ep)   # expert dim
                else:
                    place(w)
            if hasattr(layer.mlp.gate, "weight"):
                place(layer.mlp.gate.weight)
        else:
            place(layer.mlp.gate_proj.weight, 1)
            place(layer.mlp.up_proj.weight, 1)
            place(layer.mlp.down_proj.weight, 0)
    return model
