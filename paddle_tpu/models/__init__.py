"""Model zoo.

Reference: the auto-parallel Llama fixture
(test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py) and
paddle.vision.models. The LLM families live here; vision models under
paddle_tpu.vision.models.
"""
from .generation import generate
from .llama import (
    LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaDecoderLayer,
    LlamaAttention, LlamaMLP, llama_shard_plan,
)
from .bert import (
    BertConfig, BertModel, BertForPretraining,
    BertForSequenceClassification, BertEmbeddings, BertEncoderLayer,
    bert_shard_plan,
)
from .gpt import (
    GPTConfig, GPTModel, GPTForCausalLM, GPTDecoderLayer, gpt_shard_plan,
)
from .unet_diffusion import (
    DDPMScheduler, UNet2DConditionModel, UNetConfig,
)
from .ernie_moe import (
    ErnieMoeConfig, ErnieMoeForCausalLM, ErnieMoeModel, ernie_moe_shard_plan,
)
