"""paddle.reader parity — composable reader (generator-factory) decorators.

Reference: python/paddle/reader/decorator.py (cache, map_readers, shuffle,
chain, compose, buffered, firstn, xmap_readers, multiprocess_reader) and
python/paddle/batch.py (paddle.batch). A "reader" is a zero-arg callable
returning an iterator of samples; decorators wrap readers into new readers.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "multiprocess_reader", "batch",
    "ComposeNotAligned",
]


def batch(reader, batch_size, drop_last=False):
    """Group samples into mini-batches. Reference: python/paddle/batch.py:23."""
    if batch_size <= 0 or int(batch_size) != batch_size:
        raise ValueError("batch_size should be a positive integer")

    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def cache(reader):
    """Materialize the reader's samples in memory on first pass."""
    all_data = tuple(reader())

    def cache_reader():
        yield from all_data

    return cache_reader


def map_readers(func, *readers):
    """Yield func applied across the zipped outputs of the readers."""

    def reader():
        rs = [r() for r in readers]
        yield from map(func, *rs)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle within windows of buf_size samples."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers back to back."""

    def reader():
        yield from itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into tuple samples; flattens tuple-valued components."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(x) for x in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned.")
                yield sum((make_tuple(x) for x in outputs), ())

    return reader


def buffered(reader, size):
    """Read-ahead buffer of `size` samples filled by a daemon thread."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with `process_num` worker threads.

    Reference semantics (python/paddle/reader/decorator.py:479): workers pull
    samples from an input queue, apply mapper, push to an output queue;
    `order=True` preserves sample order.
    """
    def data_reader():
        # per-iteration queues: a shared input queue would let an abandoned
        # earlier iteration's workers steal samples from a later one
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        end = object()

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending: dict[int, object] = {}
            next_i = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, mapped = item
                pending[i] = mapped
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers concurrently (thread-backed here: samples
    are numpy-producing Python generators, so the GIL-bound thread pool
    matches the reference's throughput role without fork hazards under JAX)."""

    def data_reader():
        out_q: queue.Queue = queue.Queue(queue_size)
        end = object()

        def work(r):
            for sample in r():
                out_q.put(sample)
            out_q.put(end)

        for r in readers:
            threading.Thread(target=work, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = out_q.get()
            if item is end:
                finished += 1
            else:
                yield item

    return data_reader
