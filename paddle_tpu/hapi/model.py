"""High-level Model API (paddle.Model).

Reference: python/paddle/hapi/model.py — Model(network) + prepare/fit/
evaluate/predict/save/load/summary, metric integration, callback hooks.
The reference maintains separate dygraph/static adapters; here there is one
path: eager steps that the user can opt into compiling (the fit loop uses
the framework's jit-free eager path by default for robustness — batch
shapes from user datasets vary, and XLA recompiles per shape; pass
``jit_compile=True`` to fit/prepare when shapes are static).
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..io.dataloader import DataLoader
from ..metric import Metric
from ..nn.layer import Layer
from .callbacks import config_callbacks

__all__ = ["Model", "summary"]


def _to_tensor_list(data):
    if isinstance(data, (list, tuple)):
        return [t if isinstance(t, Tensor) else Tensor._from_value(np.asarray(t))
                for t in data]
    return [data if isinstance(data, Tensor)
            else Tensor._from_value(np.asarray(data))]


class Model:
    """Reference: hapi/model.py Model(network, inputs=None, labels=None)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- configuration ----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            metrics = []
        elif isinstance(metrics, Metric):
            metrics = [metrics]
        self._metrics = list(metrics)

    # -- single-batch ops (reference train_batch/eval_batch/predict_batch) -
    def train_batch(self, inputs, labels=None, update=True, loss_scale=1.0):
        self.network.train()
        inputs = _to_tensor_list(inputs)
        labels = _to_tensor_list(labels) if labels is not None else []
        outputs = self.network(*inputs)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*(list(outs) + labels))
        if isinstance(loss, (list, tuple)):
            loss = loss[0]
        loss = loss.mean() if loss.ndim > 0 else loss
        (loss * loss_scale if loss_scale != 1.0 else loss).backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return ([float(loss._value)], metrics) if metrics else [float(loss._value)]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        import paddle_tpu as paddle

        with paddle.no_grad():
            inputs = _to_tensor_list(inputs)
            labels = _to_tensor_list(labels) if labels is not None else []
            outputs = self.network(*inputs)
            outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            losses = None
            if self._loss is not None and labels:
                loss = self._loss(*(list(outs) + labels))
                if isinstance(loss, (list, tuple)):
                    loss = loss[0]
                losses = [float((loss.mean() if loss.ndim > 0 else loss)._value)]
            metrics = self._update_metrics(outs, labels)
        return (losses, metrics) if metrics else losses

    def predict_batch(self, inputs):
        self.network.eval()
        import paddle_tpu as paddle

        with paddle.no_grad():
            outputs = self.network(*_to_tensor_list(inputs))
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [np.asarray(o._value) for o in outs]

    def _update_metrics(self, outs, labels):
        results = []
        for metric in self._metrics:
            res = metric.compute(*(list(outs) + labels))
            if not isinstance(res, (list, tuple)):
                res = [res]
            metric.update(*[np.asarray(r._value) if isinstance(r, Tensor)
                            else np.asarray(r) for r in res])
            results.append(metric.accumulate())
        return results

    # -- loops -------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers,
                   drop_last=False):
        if data is None or isinstance(data, DataLoader):
            return data
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            # already an iterable of batches (generator-style loader), not a
            # map-style Dataset — use as-is
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        # dataset batches are (inputs..., label) like the reference contract
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers,
                                 drop_last=drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers)
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=len(loader) if hasattr(loader, "__len__") else None,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        try:
            self._fit_loop(loader, eval_loader, cbks, epochs, eval_freq,
                           num_iters, accumulate_grad_batches)
        except BaseException as e:
            cbks.on_train_abort(e)
            raise
        cbks.on_train_end()

    def _fit_loop(self, loader, eval_loader, cbks, epochs, eval_freq,
                  num_iters, accumulate_grad_batches):
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            it = 0
            logs = {}
            pending_update = False
            n_batches = len(loader) if hasattr(loader, "__len__") else None
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                # flush on group boundary AND at epoch end so no gradient
                # group leaks across epochs (reference applies per N or tail)
                last = (n_batches is not None and step == n_batches - 1) or (
                    num_iters is not None and it + 1 >= num_iters)
                update = ((step + 1) % accumulate_grad_batches == 0) or last
                res = self.train_batch(inputs, labels, update=update,
                                       loss_scale=1.0 / accumulate_grad_batches
                                       if accumulate_grad_batches > 1 else 1.0)
                pending_update = not update
                logs = self._logs(res, batch_size=self._batch_len(inputs))
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            if pending_update and self._optimizer is not None:
                # loaders without __len__ can end mid-group: flush the tail
                self._optimizer.step()
                self._optimizer.clear_grad()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cbks)
            if self.stop_training:
                break

    def _run_eval(self, loader, cbks):
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            res = self.eval_batch(inputs, labels)
            logs = self._logs(res, batch_size=self._batch_len(inputs))
            cbks.on_eval_batch_end(step, logs)
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._as_loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                metrics=[m.name() for m in self._metrics])
        logs = self._run_eval(loader, cbks)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    @staticmethod
    def _batch_len(inputs):
        try:
            return int(inputs[0].shape[0])
        except Exception:
            return 0

    def _logs(self, res, batch_size=0):
        logs = {"batch_size": batch_size}
        if isinstance(res, tuple):
            losses, metrics = res
            if losses:
                logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                logs[m.name() if not isinstance(m.name(), list) else
                     m.name()[0]] = v
        elif res is not None:
            logs["loss"] = res[0]
        return logs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        """Reference: hapi/model.py save — `path.pdparams` (+ `.pdopt`)."""
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        import paddle_tpu as paddle

        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle

        state = paddle.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(paddle.load(opt_path))

    def parameters(self, include_sublayers=True):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Reference: hapi/model_summary.py — layer table + param counts."""
    rows = []
    total, trainable = 0, 0
    for name, param in net.named_parameters():
        n = int(np.prod(param.shape))
        total += n
        if not param.stop_gradient:
            trainable += n
        rows.append((name, list(param.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Params':>12}"]
    lines += [f"{name:<{width}}{str(shape):<20}{n:>12,}"
              for name, shape, n in rows]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
