"""hapi callbacks.

Reference: python/paddle/hapi/callbacks.py (Callback base, ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL writer). VisualDL is
replaced by a no-op logger (the visualdl package is GPU-stack tooling);
everything else is behavior-parity.
"""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "MetricsCallback",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_train_abort(self, exc=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Reference: hapi/callbacks.py ProgBarLogger — per-step metric lines
    with ips (images/sec) like profiler/timer.py Benchmark."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step = 0
        self._t0 = time.time()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self._step += 1
        self._samples += logs.get("batch_size", 0)
        if self.verbose and self._step % self.log_freq == 0:
            dt = max(time.time() - self._t0, 1e-9)
            items = [f"{k}: {self._fmt(v)}" for k, v in logs.items()
                     if k not in ("batch_size",)]
            ips = f"{self._samples / dt:.1f} samples/sec" if self._samples else ""
            print(f"Epoch {self._epoch} step {self._step}: "
                  + ", ".join(items) + (f" | {ips}" if ips else ""))

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            items = [f"{k}: {self._fmt(v)}" for k, v in logs.items()]
            print("Eval: " + ", ".join(items))

    @staticmethod
    def _fmt(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
        if isinstance(v, numbers.Number):
            return f"{float(v):.4f}"
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait_epoch = 0
        self.best_value = None
        self.save_dir = None  # set by config_callbacks

    def _better(self, cur, best):
        delta = self.min_delta
        return cur < best - delta if self.mode == "min" else cur > best + delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        value = float(np.ravel(value)[0])
        if self.best_value is None or self._better(value, self.best_value):
            self.best_value = value
            self.wait_epoch = 0
            if self.save_best_model and self.save_dir and self.model:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: stop, best {self.monitor}="
                      f"{self.best_value:.5f}")


class MetricsCallback(Callback):
    """Streams ``Model.fit`` step telemetry through
    ``paddle_tpu.observability``: every train batch is bracketed by a
    :class:`~paddle_tpu.observability.StepTimer` region, recording
    ``train.step_seconds``, ``train.items_per_second`` (samples/sec from
    the loop's ``batch_size`` log) and — when ``flops_per_step`` is
    given — ``train.mfu``; device memory gauges are sampled every
    ``sample_memory_every`` steps. No-op while observability is
    disabled, so it is safe to leave in production callback lists.

    ``flops_per_step`` can be a number or a zero-arg callable evaluated
    lazily at train begin (e.g. ``lambda:
    obs.measure_step_flops(step_fn, *sample_batch)``).
    """

    def __init__(self, name="fit", flops_per_step=None, peak_flops=None,
                 sample_memory_every=16, unit="samples"):
        super().__init__()
        self._name = name
        self._flops = flops_per_step
        self._peak = peak_flops
        self._every = sample_memory_every
        self._unit = unit
        self._timer = None

    def on_train_begin(self, logs=None):
        import paddle_tpu.observability as obs

        if not obs.enabled():
            # stay a true no-op: in particular don't evaluate a
            # flops_per_step callable (it may XLA-compile the step fn)
            self._timer = None
            return
        flops = self._flops() if callable(self._flops) else self._flops
        self._timer = obs.StepTimer(
            self._name, flops_per_step=flops, peak_flops=self._peak,
            unit=self._unit, sample_memory_every=self._every)

    def on_train_batch_begin(self, step, logs=None):
        if self._timer is not None:
            self._timer.begin()

    def on_train_batch_end(self, step, logs=None):
        if self._timer is not None:
            self._timer.end(items=(logs or {}).get("batch_size") or None)
            # fleet telemetry: ship this worker's snapshot at the step
            # boundary (rate-limited; a no-op without an active
            # FleetReporter and never raises into the fit loop)
            from paddle_tpu.observability import fleet as _fleet

            _fleet.maybe_ship()

    def on_train_abort(self, exc=None):
        # fit died between batch-begin and batch-end: close the open
        # region as failed so the span stack stays balanced and the
        # step_exception flight dump is written even when the caller
        # catches the exception (no excepthook fires then)
        if self._timer is not None:
            self._timer.end(failed=True)
        self._timer = None

    def on_train_end(self, logs=None):
        import paddle_tpu.observability as obs

        if self._timer is not None:
            self._timer.abandon()  # batch-end never came for an open step
            if obs.enabled():
                obs.sample_device_memory()
                # push one fresh snapshot carrying the end-of-train state
                obs.fleet.maybe_ship(min_interval_s=0.0)
        self._timer = None


class VisualDL(Callback):
    """Logging stub with the reference's VisualDL callback surface — records
    scalars into an in-memory dict (`.scalars`) instead of a visualdl run."""

    def __init__(self, log_dir=None):
        super().__init__()
        self.log_dir = log_dir
        self.scalars = {}

    def on_train_batch_end(self, step, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self.scalars.setdefault(f"train/{k}", []).append(float(v))

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, (numbers.Number, np.ndarray, list)):
                self.scalars.setdefault(f"eval/{k}", []).append(
                    float(np.ravel(v)[0]))


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if mode == "train" and not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    for c in cbks:
        if isinstance(c, EarlyStopping):
            c.save_dir = save_dir
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
