"""paddle.hapi parity. Reference: python/paddle/hapi/__init__.py."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    VisualDL,
)
from .model import Model, summary  # noqa: F401
