"""Signal processing — ``paddle.signal`` parity.

Reference surface: python/paddle/signal.py (frame :38, overlap_add :161,
stft :266, istft :443). The reference frames via a dedicated phi kernel;
here framing is a strided gather and overlap-add a scatter-add, both of
which XLA lowers to fused TPU programs. stft/istft are registered as
primitives so the framework autograd (jax.vjp fallback) differentiates
through the whole frame→window→FFT chain, matching the reference's
differentiable stft.

Axis contract (reference frame :44-65): ``axis`` must be 0 or -1;
axis=-1 frames ``[..., seq]`` → ``[..., frame_length, num_frames]``,
axis=0 frames ``[seq, ...]`` → ``[num_frames, frame_length, ...]``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor, apply
from .ops._helpers import defprim, ensure_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_last(x, frame_length, hop_length):
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(num_frames) * hop_length)[:, None] + jnp.arange(frame_length)[None, :]
    frames = x[..., idx]           # (..., num_frames, frame_length)
    return jnp.swapaxes(frames, -1, -2)  # (..., frame_length, num_frames)


def _frame_first(x, frame_length, hop_length):
    # (seq, ...) -> (num_frames, frame_length, ...)
    y = _frame_last(jnp.moveaxis(x, 0, -1), frame_length, hop_length)
    return jnp.moveaxis(y, (-2, -1), (1, 0))


defprim(
    "frame_p",
    lambda x, *, frame_length, hop_length, axis: (
        _frame_last(x, frame_length, hop_length)
        if axis == -1 or (axis == x.ndim - 1 and x.ndim > 1)
        else (
            jnp.swapaxes(_frame_last(x, frame_length, hop_length), 0, 1)
            if x.ndim == 1
            else _frame_first(x, frame_length, hop_length)
        )
    ),
)


def _overlap_add_last(x, hop_length):
    # x: (..., frame_length, num_frames)
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    idx = (jnp.arange(num_frames) * hop_length)[None, :] + jnp.arange(frame_length)[:, None]
    flat = x.reshape(x.shape[:-2] + (frame_length * num_frames,))
    out = jnp.zeros(x.shape[:-2] + (out_len,), dtype=x.dtype)
    return out.at[..., idx.reshape(-1)].add(flat)


defprim(
    "overlap_add_p",
    lambda x, *, hop_length, axis: (
        _overlap_add_last(x, hop_length)
        if axis == -1 or axis == x.ndim - 1
        else jnp.moveaxis(
            _overlap_add_last(jnp.moveaxis(x, (1, 0), (-2, -1)), hop_length), -1, 0
        )
    ),
)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    x = ensure_tensor(x)
    if axis not in (0, -1):
        raise ValueError(f"Attribute axis should be 0 or -1, but got ({axis}).")
    if frame_length > x.shape[axis]:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence length, "
            f"but got ({frame_length}) > ({x.shape[axis]})."
        )
    if hop_length <= 0:
        raise ValueError(f"Attribute hop_length should be greater than 0, but got ({hop_length}).")
    return apply("frame_p", x, frame_length=int(frame_length),
                 hop_length=int(hop_length), axis=int(axis))


def overlap_add(x, hop_length, axis=-1, name=None):
    x = ensure_tensor(x)
    if axis not in (0, -1):
        raise ValueError(f"Attribute axis should be 0 or -1, but got ({axis}).")
    if x.ndim < 2:
        raise ValueError(f"Input x should be at least 2D, but got rank {x.ndim}.")
    if hop_length <= 0:
        raise ValueError(f"Attribute hop_length should be greater than 0, but got ({hop_length}).")
    return apply("overlap_add_p", x, hop_length=int(hop_length), axis=int(axis))


def _padded_window(w, win_length, n_fft, dtype):
    if w is None:
        w = jnp.ones((win_length,), dtype=dtype)
    if w.shape[0] != win_length:
        raise ValueError(
            f"Expected window length {win_length} (win_length), but got {w.shape[0]}."
        )
    pad = n_fft - w.shape[0]
    return jnp.pad(w, (pad // 2, pad - pad // 2))


def _resolve_hop(hop_length, n_fft):
    hop = n_fft // 4 if hop_length is None else int(hop_length)
    if hop <= 0:
        raise ValueError(
            f"Attribute hop_length should be greater than 0, but got ({hop})."
        )
    return hop


def _stft_fwd(sig, w, *, n_fft, hop_length, center, pad_mode, normalized, onesided):
    squeeze = sig.ndim == 1
    if squeeze:
        sig = sig[None, :]
    if center:
        p = n_fft // 2
        sig = jnp.pad(sig, ((0, 0), (p, p)), mode=pad_mode)
    frames = _frame_last(sig, n_fft, hop_length)        # (B, n_fft, F)
    frames = frames * w[None, :, None].astype(frames.dtype)
    spec = (jnp.fft.rfft if onesided else jnp.fft.fft)(frames, axis=1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return spec[0] if squeeze else spec


defprim("stft_p", _stft_fwd)


def _istft_fwd(spec, w, *, n_fft, hop_length, center, normalized, onesided,
               return_complex, length):
    squeeze = spec.ndim == 2
    if squeeze:
        spec = spec[None]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=1)    # (B, n_fft, F)
    else:
        frames = jnp.fft.ifft(spec, axis=1)
        if not return_complex:
            frames = frames.real
    frames = frames * w[None, :, None].astype(frames.dtype)
    sig = _overlap_add_last(frames, hop_length)          # (B, T)
    wsq = jnp.tile((w * w)[:, None], (1, spec.shape[-1]))
    env = _overlap_add_last(wsq[None], hop_length)[0]
    sig = sig / jnp.where(jnp.abs(env) > 1e-11, env, 1.0).astype(sig.dtype)
    if center:
        p = n_fft // 2
        sig = sig[:, p:sig.shape[1] - p]
    if length is not None:
        sig = sig[:, :length]
    return sig[0] if squeeze else sig


defprim("istft_p", _istft_fwd)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    x = ensure_tensor(x)
    if x.ndim not in (1, 2):
        raise ValueError(f"x should be a 1D or 2D real tensor, but got rank {x.ndim}")
    hop_length = _resolve_hop(hop_length, n_fft)
    win_length = win_length or n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(f"Expected 0 < win_length <= n_fft, but got win_length={win_length}")
    is_complex = np.dtype(x.dtype).kind == "c"
    wt = None if window is None else ensure_tensor(window)
    if wt is not None and np.dtype(wt.dtype).kind == "c":
        is_complex = True
    if is_complex and onesided:
        raise ValueError("onesided should be False when input or window is a complex Tensor")
    sig_len = x.shape[-1] + (2 * (n_fft // 2) if center else 0)
    if sig_len < n_fft:
        raise ValueError(
            f"Input size should be equal or greater than n_fft, but got input length "
            f"{x.shape[-1]} < n_fft {n_fft} (center={center})."
        )
    if wt is None:
        wt = Tensor._from_value(jnp.ones((win_length,), dtype=np.dtype("float32")))
    w_padded = Tensor._from_value(_padded_window(wt._value, win_length, n_fft, wt._value.dtype))
    return apply("stft_p", x, w_padded, n_fft=int(n_fft), hop_length=int(hop_length),
                 center=bool(center), pad_mode=str(pad_mode),
                 normalized=bool(normalized), onesided=bool(onesided))


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    x = ensure_tensor(x)
    if x.ndim not in (2, 3):
        raise ValueError(f"x should be a 2D or 3D complex tensor, but got rank {x.ndim}")
    if return_complex and onesided:
        raise ValueError(
            "onesided should be False when return_complex is True (a onesided "
            "spectrogram reconstructs a real signal)."
        )
    hop_length = _resolve_hop(hop_length, n_fft)
    win_length = win_length or n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(f"Expected 0 < win_length <= n_fft, but got win_length={win_length}")
    n_bins = x.shape[-2]
    expected = n_fft // 2 + 1 if onesided else n_fft
    if n_bins != expected:
        raise ValueError(
            f"Expected {expected} frequency bins (n_fft={n_fft}, onesided={onesided}), "
            f"but got {n_bins}."
        )
    if window is not None:
        wt = ensure_tensor(window)
    else:
        wt = Tensor._from_value(jnp.ones((win_length,), dtype=np.dtype("float32")))
    w_padded = Tensor._from_value(_padded_window(wt._value, win_length, n_fft, wt._value.dtype))
    import jax.core as _jcore

    if not isinstance(w_padded._value, _jcore.Tracer):
        # NOLA check (reference istft raises on a degenerate window envelope);
        # runs eagerly on the concrete window — inside jit we can't raise.
        wv = w_padded._value
        env = _overlap_add_last((wv * wv)[:, None] * jnp.ones((1, x.shape[-1])), hop_length)
        interior = env[n_fft // 2 : env.shape[0] - n_fft // 2] if center else env
        if interior.size and float(jnp.min(jnp.abs(interior))) < 1e-11:
            raise ValueError(
                "window overlap-add envelope is (near) zero — the window/"
                "hop_length combination violates the NOLA constraint."
            )
    return apply("istft_p", x, w_padded, n_fft=int(n_fft), hop_length=int(hop_length),
                 center=bool(center), normalized=bool(normalized),
                 onesided=bool(onesided), return_complex=bool(return_complex),
                 length=None if length is None else int(length))
