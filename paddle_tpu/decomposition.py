"""Composite-op decomposition — ``paddle.decomposition`` parity.

Reference: python/paddle/decomposition/ (decompose rules lowering composite
ops to primitive ops for the compiler and higher-order autodiff;
fluid/prim + fluid/primitive in C++). In this framework XLA is the
primitive layer — every registered primitive already lowers to StableHLO,
and higher-order autodiff runs through nested jax.vjp — so decomposition is
a *view*, not a rewrite: ``decompose_rule`` registers a pure-primitive
expansion, and ``decompose`` re-expresses a captured static Program with
those expansions applied (useful for inspecting what a composite op does
and for excluding fused kernels from a compiled program)."""
from __future__ import annotations

from typing import Callable, Dict, List

__all__ = ["register_decomp", "get_decomp_rule", "has_decomp", "decompose"]

_RULES: Dict[str, Callable] = {}


def register_decomp(op_name: str):
    """Register a decomposition rule: fn(*input_tensors, **static) returning
    the composite's outputs built only from primitive ops."""

    def wrapper(fn):
        _RULES[op_name] = fn
        return fn

    return wrapper


def has_decomp(op_name: str) -> bool:
    return op_name in _RULES


def get_decomp_rule(op_name: str):
    return _RULES.get(op_name)


def decompose(program, ops_filter=None):
    """Rewrite a captured static Program, replacing each instruction whose
    op has a registered rule (and passes ops_filter) by re-tracing the rule
    under capture — the instruction expands into primitive instructions in
    a NEW Program (reference: decomposition/decompose.py rewriting a
    pir::Program in place). Fetch targets from the original trace remain
    resolvable against the returned program."""
    from .core import dispatch as _dispatch
    from .core.tensor import Tensor
    from .static.program import Program

    if not isinstance(program, Program):
        raise TypeError("decompose expects a paddle_tpu.static.Program")

    new = Program()
    env = {}  # old vid -> value object in the new trace
    for name, vid, shape, dtype in program._placeholders:
        env[vid] = new.add_placeholder(name, shape, dtype)
    for vid, const in program._consts.items():
        env[vid] = const

    prev_capture = _dispatch._capture_program
    _dispatch.set_capture_program(new)
    try:
        for prim_name, in_vids, static_items, out_vids in program._insts:
            static = dict(static_items)
            ins = tuple(env[v] for v in in_vids)
            rule = _RULES.get(prim_name)
            if rule is not None and (ops_filter is None or prim_name in ops_filter):
                touts = rule(*(Tensor._from_value(a) for a in ins), **static)
                touts = touts if isinstance(touts, (tuple, list)) else (touts,)
                outs = tuple(t._value for t in touts)
            else:
                outs = _dispatch.call_primitive(prim_name, ins, static)
                outs = outs if isinstance(outs, tuple) else (outs,)
            for ov, o in zip(out_vids, outs):
                env[ov] = o
    finally:
        _dispatch.set_capture_program(prev_capture)

    # keep fetch Tensors from the ORIGINAL trace resolvable: alias each old
    # captured object's id to the corresponding new vid
    for obj in program._keepalive:
        old_vid = program._vid_by_obj.get(id(obj))
        if old_vid is None or old_vid not in env:
            continue
        new_vid = new._vid_by_obj.get(id(env[old_vid]))
        if new_vid is not None:
            new._vid_by_obj[id(obj)] = new_vid
            new._keepalive.append(obj)
    return new


# -- built-in rules for the fused primitives (inspection/reference) --------
@register_decomp("softmax_p")
def _softmax_rule(x, *, axis=-1):
    from .ops.math import exp, max as max_, sum as sum_

    z = exp(x - max_(x, axis=axis, keepdim=True))
    return z / z.sum(axis=axis, keepdim=True)


@register_decomp("gelu_p")
def _gelu_rule(x, *, approximate=False):
    import math

    from .ops.math import erf, pow as pow_, tanh

    if approximate:
        return 0.5 * x * (1.0 + tanh(math.sqrt(2.0 / math.pi) * (x + 0.044715 * pow_(x, 3.0))))
    return 0.5 * x * (1.0 + erf(x / math.sqrt(2.0)))
