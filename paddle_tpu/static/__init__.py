"""paddle.static parity.

Reference: python/paddle/static/ — Program/Executor/program_guard/data
(static graph build + run, SURVEY §3.3) plus InputSpec. The capture
machinery lives in program.py; save/load_inference_model bridge to the
jit.save StableHLO format consumed by paddle_tpu.inference.
"""
from __future__ import annotations

import contextlib

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .program import (  # noqa: F401
    Executor, Program, data, default_main_program, default_startup_program,
    program_guard,
)
from . import analysis  # noqa: F401
from .analysis import (  # noqa: F401
    ProgramVerificationError, check_program, run_lints, verify_program,
)

__all__ = [
    "InputSpec", "Program", "Executor", "data", "program_guard",
    "default_main_program", "default_startup_program", "name_scope",
    "save_inference_model", "load_inference_model",
    "analysis", "verify_program", "check_program", "run_lints",
    "ProgramVerificationError",
]


def name_scope(name):
    return contextlib.nullcontext()


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, program=None, **kwargs):
    """Reference: paddle.static.save_inference_model.

    Two artifact kinds, matching the two capture modes:
    - dynamic layer (kwargs['layer']): jit.save payload (params +
      StableHLO);
    - captured static Program (``program=...`` or the current default
      main program): normalize to the feed->fetch slice and write
      <prefix>.pdmodel/.pdparams — the form inference.Predictor's
      analysis pipeline consumes."""
    layer = kwargs.get("layer")
    if layer is not None:
        from .. import jit

        jit.save(layer, path_prefix, input_spec=feed_vars)
        return
    from .extras import normalize_program, save
    from .program import default_main_program

    program = program or default_main_program()
    pruned = normalize_program(program, feed_vars, fetch_vars)
    save(pruned, path_prefix)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Reference: paddle.static.load_inference_model — returns
    (program-or-fn, feed_names, fetch_targets). Static .pdmodel
    programs come back as a Program (run via static.Executor with the
    returned fetch vids); jit.save payloads come back as the loaded
    callable."""
    from .extras import load_static_artifact

    prog = load_static_artifact(path_prefix)
    if prog is not None:
        feed_names = [n for n, _v, _s, _d in prog._placeholders]
        return prog, feed_names, list(getattr(prog, "_fetch_vids", ()))
    from .. import jit

    fn = jit.load(path_prefix)
    return fn, [], []

from .extras import (  # noqa: F401
    append_backward, gradients, Scope, global_scope, scope_guard,
    BuildStrategy, CompiledProgram, Print, py_func, WeightNormParamAttr,
    ExponentialMovingAverage, save, load, serialize_program,
    serialize_persistables, save_to_file, deserialize_program,
    deserialize_persistables, load_from_file, normalize_program,
    load_program_state, set_program_state, cpu_places, cuda_places,
    xpu_places, Variable, create_global_var, accuracy, auc, device_guard,
    ipu_shard_guard, set_ipu_shard, IpuCompiledProgram, IpuStrategy,
    ctr_metric_bundle,
)
from ..framework.misc import create_parameter  # noqa: F401
