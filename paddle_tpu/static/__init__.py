"""paddle.static parity (thin).

Reference: python/paddle/static/ — the reference's separate static-graph
mode (Program/Executor) collapses into jit.to_static on this framework
(SURVEY §7 design stance): InputSpec describes traced inputs, and the
Executor/Program surface is kept as a compatibility veneer over compiled
functions for code being ported.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401


class Program:
    """Placeholder for ported code; real capture goes through jit.to_static."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        raise NotImplementedError(
            "static Executor is not part of the TPU framework; decorate the "
            "model with paddle_tpu.jit.to_static instead (SURVEY §7)"
        )


def name_scope(name):
    import contextlib

    return contextlib.nullcontext()
