"""paddle.static parity.

Reference: python/paddle/static/ — Program/Executor/program_guard/data
(static graph build + run, SURVEY §3.3) plus InputSpec. The capture
machinery lives in program.py; save/load_inference_model bridge to the
jit.save StableHLO format consumed by paddle_tpu.inference.
"""
from __future__ import annotations

import contextlib

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .program import (  # noqa: F401
    Executor, Program, data, default_main_program, default_startup_program,
    program_guard,
)

__all__ = [
    "InputSpec", "Program", "Executor", "data", "program_guard",
    "default_main_program", "default_startup_program", "name_scope",
    "save_inference_model", "load_inference_model",
]


def name_scope(name):
    return contextlib.nullcontext()


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, **kwargs):
    """Reference: paddle.static.save_inference_model. The TPU framework's
    inference artifact is the jit.save payload (params + StableHLO); pass
    the source layer via kwargs['layer'] or export with paddle_tpu.jit.save
    directly."""
    layer = kwargs.get("layer")
    if layer is None:
        raise NotImplementedError(
            "save_inference_model for raw static programs is not supported; "
            "export the model with paddle_tpu.jit.save(layer, path, "
            "input_spec=...) and serve it with paddle_tpu.inference"
        )
    from .. import jit

    jit.save(layer, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit

    fn = jit.load(path_prefix)
    return fn, [], []

from .extras import (  # noqa: F401
    append_backward, gradients, Scope, global_scope, scope_guard,
    BuildStrategy, CompiledProgram, Print, py_func, WeightNormParamAttr,
    ExponentialMovingAverage, save, load, serialize_program,
    serialize_persistables, save_to_file, deserialize_program,
    deserialize_persistables, load_from_file, normalize_program,
    load_program_state, set_program_state, cpu_places, cuda_places,
    xpu_places, Variable, create_global_var, accuracy, auc, device_guard,
    ipu_shard_guard, set_ipu_shard, IpuCompiledProgram, IpuStrategy,
    ctr_metric_bundle,
)
from ..framework.misc import create_parameter  # noqa: F401
