"""Serve-trace lint: scheduling pathologies read off a serve_trace dump.

The serving sibling of ``sharding_lint.lint_fleet_trace`` (PTL203): where
that lint reads the merged fleet Chrome trace for collectives that
serialize against compute, this one reads the ``serve_trace`` dump a
:class:`~paddle_tpu.observability.tracing.ServeTracer` writes
(``tools/serve_load.py --trace-out``) for the two pathologies the
continuous-batching engine can hide inside healthy-looking aggregates:

- **PTL404 — decode-burst gaps**: consecutive batched decode steps with
  host-side dead time between them while the previous step left runnable
  slots behind. The chip sits idle while the host runs admission,
  sampling and bookkeeping — exactly the signal that motivates the
  ROADMAP's fused multi-token decode item (``lax.scan`` bursts between
  scheduler passes).
- **PTL405 — preemption thrash**: one request preempted >= K times. Each
  preemption throws away that stream's KV blocks and bills a full
  recompute prefill on resume; a request evicted over and over is paying
  for pool pressure the admission policy should have absorbed.

``tools/metrics_report.py --serve-trace DIR`` runs this lint next to the
per-phase breakdown table, the same way ``--fleet`` runs the PTL203 lint
on ``fleet_trace.json``.
"""
from __future__ import annotations

from typing import Any, Dict, List

from .diagnostics import DiagnosticReport, Severity

__all__ = ["lint_serve_trace", "SERVE_TRACE_LINT_CODES"]

#: codes this lint emits — documented in diagnostics.CODES; the
#: registration is audited by tools/lint_registry.py
SERVE_TRACE_LINT_CODES = ("PTL404", "PTL405")

#: stop after this many PTL404 findings per dump: one systemic host-side
#: stall produces a gap after EVERY step, and 4000 copies of the same
#: finding bury the report (the truncation is announced as a NOTE)
_MAX_GAP_FINDINGS = 8


def _median(vals: List[float]) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def lint_serve_trace(doc: Dict[str, Any], *,
                     min_gap_seconds: float = 0.010,
                     gap_ratio: float = 4.0,
                     thrash_k: int = 3) -> DiagnosticReport:
    """Lint one ``serve_trace`` dump (the ``ServeTracer.dump_dict()``
    JSON). A decode gap is flagged when it exceeds both
    ``min_gap_seconds`` and ``gap_ratio`` x the median decode-step
    duration (short host turnarounds are the engine working as designed;
    a gap several steps long is the chip waiting on the host). A request
    is thrash when preempted >= ``thrash_k`` times."""
    report = DiagnosticReport()
    if not isinstance(doc, dict) or doc.get("kind") != "serve_trace":
        raise ValueError(
            f"lint_serve_trace wants a serve_trace dump, got "
            f"kind={doc.get('kind') if isinstance(doc, dict) else type(doc).__name__!r}")

    steps = doc.get("decode_steps") or []
    durs = [float(s["end"]) - float(s["start"]) for s in steps]
    med = _median(durs)
    threshold = max(min_gap_seconds, gap_ratio * med)
    n_gaps = 0
    for prev, nxt in zip(steps, steps[1:]):
        if int(prev.get("active", 0)) <= 0:
            continue        # slots drained: waiting on arrivals, not host
        gap = float(nxt["start"]) - float(prev["end"])
        if gap <= threshold:
            continue
        n_gaps += 1
        if n_gaps <= _MAX_GAP_FINDINGS:
            report.add(
                "PTL404", Severity.WARNING,
                f"decode-burst gap: {gap * 1e3:.2f} ms host-side between "
                f"decode steps at t={float(prev['end']):.4f}s with "
                f"{prev.get('active')} runnable slot(s) "
                f"(median step {med * 1e3:.2f} ms)",
                hint="the engine loop is host-driven — one device "
                     "round-trip per token; fuse N-token decode bursts "
                     "(lax.scan) between scheduler passes so steady-state "
                     "decode never leaves the chip",
                suggestion={"gap_seconds": round(gap, 6),
                            "at": float(prev["end"]),
                            "active": int(prev.get("active", 0))})
    if n_gaps > _MAX_GAP_FINDINGS:
        report.add(
            "PTL404", Severity.NOTE,
            f"{n_gaps - _MAX_GAP_FINDINGS} further decode-burst gap(s) "
            f"over the same threshold suppressed — the stall is "
            f"systemic, not incidental",
            suggestion={"suppressed": n_gaps - _MAX_GAP_FINDINGS})

    for r in doc.get("requests") or []:
        k = int(r.get("preemptions") or 0)
        if k >= thrash_k:
            recompute = (r.get("breakdown") or {}).get("recompute", 0.0)
            report.add(
                "PTL405", Severity.WARNING,
                f"preemption thrash: request {r.get('id')} preempted "
                f"{k} time(s) (>= {thrash_k}), paying "
                f"{float(recompute) * 1e3:.2f} ms of recompute prefill",
                hint="grow the KV pool (--num_blocks), lower the slot "
                     "count, or gate admission on projected working "
                     "set — youngest-first eviction is starving this "
                     "stream's pool residency",
                suggestion={"request": r.get("id"), "preemptions": k})
    return report
