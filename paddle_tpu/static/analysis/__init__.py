"""paddle_tpu.static.analysis — Program IR verifier + lint/diagnostics.

The correctness-tooling layer for the captured static ``Program``
(static/program.py). Every component maps onto a reference-framework
analog:

===================  ======================================================
component            reference analog
===================  ======================================================
``verify.py``        the PIR verifier ``pir::PassManager`` runs between
                     passes (pir/include/pass/pass_manager.h:35 — op
                     VerifySig/VerifyRegion + region walk); here a single
                     forward walk over the flat instruction list plus an
                     InferMeta audit that re-runs ``dispatch.eval_shape``
                     (the InferMetaInterface analog) per instruction.
``lint.py``          the read-only analysis passes of the inference
                     analysis pipeline (paddle/fluid/inference/analysis/)
                     — advisory findings (dead ops, unused feeds,
                     redundant cast/transpose chains, CSE candidates,
                     fp64->fp32 demotion, non-jittable ops under jit).
``diagnostics.py``   IrNotMetException + the analysis pipeline's logging,
                     unified into coded ``PTLxxx`` Diagnostic records
                     (severity, op index, fix hint).
``ir_dump.py``       pir::Program::Print / EnableIRPrinting — the textual
                     IR that ``Program.dump()`` (and ``repr``) render, so
                     a diagnostic's ``op#N`` is readable in context.
===================  ======================================================

Integration points: ``distributed.passes.PassManager(verify=True)``
verifies every program before/after each rewrite pass and attaches the
failing pass name to the raised :class:`ProgramVerificationError`
(enabled by default when ``PADDLE_TPU_PASS_VERIFY=1``, which the test
suite sets); ``tools/lint_registry.py`` applies the same discipline to
the primitive registry itself.
"""
from __future__ import annotations

from .comm_cost import (  # noqa: F401
    COLLECTIVE_KINDS, Collective, CommCostResult, CommModelParams,
    calibrate_comm_model, collective_cost, derive_collectives,
    program_comm_cost, resolve_comm_params,
)
from .cost import (  # noqa: F401
    COST_ANALYSIS_CODES, OpCost, ProgramCost, check_cost_model,
    check_step_time_model, measure_program_flops, op_cost, program_cost,
    register_op_cost,
)
from .diagnostics import (  # noqa: F401
    CODES, Diagnostic, DiagnosticReport, ProgramVerificationError, Severity,
)
from .ir_dump import dump_program  # noqa: F401
from .memory import (  # noqa: F401
    MemoryEstimate, device_memory_budget, estimate_peak_memory,
    lint_memory_budget,
)
from .lint import (  # noqa: F401
    LintContext, lossless_cast, register_lint, run_lints,
)
from .liveness import is_effectful, live_op_indices  # noqa: F401
from .rewrite import (  # noqa: F401
    DEFAULT_PIPELINE, OptimizeResult, REWRITE_CODES, optimize_program,
)
from .serve_trace_lint import (  # noqa: F401
    SERVE_TRACE_LINT_CODES, lint_serve_trace,
)
from .sharding_lint import (  # noqa: F401
    SHARDING_LINT_CODES, apply_placement_suggestion, lint_fleet_trace,
    run_placement_lints,
)
from .verify import (  # noqa: F401
    check_program, propagate_avals, recorded_avals, verify_program,
)

__all__ = [
    "CODES", "Diagnostic", "DiagnosticReport", "ProgramVerificationError",
    "Severity", "dump_program", "LintContext", "register_lint", "run_lints",
    "check_program", "propagate_avals", "recorded_avals", "verify_program",
    "lossless_cast", "is_effectful", "live_op_indices",
    "DEFAULT_PIPELINE", "OptimizeResult", "REWRITE_CODES",
    "optimize_program",
    "SHARDING_LINT_CODES", "lint_fleet_trace", "run_placement_lints",
    "apply_placement_suggestion",
    "SERVE_TRACE_LINT_CODES", "lint_serve_trace",
    "COST_ANALYSIS_CODES", "OpCost", "ProgramCost", "check_cost_model",
    "check_step_time_model", "measure_program_flops", "op_cost",
    "program_cost", "register_op_cost",
    "MemoryEstimate", "device_memory_budget", "estimate_peak_memory",
    "lint_memory_budget",
    "COLLECTIVE_KINDS", "Collective", "CommCostResult", "CommModelParams",
    "calibrate_comm_model", "collective_cost", "derive_collectives",
    "program_comm_cost", "resolve_comm_params",
]
