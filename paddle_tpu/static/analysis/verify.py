"""Structural verifier for the captured static ``Program``.

Reference: the PIR verifier that ``pir::PassManager`` runs between passes
(pir/include/pass/pass_manager.h — EnableIRPrinting/verify hooks, op
``VerifySig``/``VerifyRegion``). The captured Program here is a flat
SSA-ish instruction list ``(prim, in_vids, static_attrs, out_vids)``
(static/program.py), so verification is a single forward walk plus an
InferMeta audit that re-runs shape inference (``dispatch.eval_shape``,
the InferMetaInterface analog) and checks the recorded result avals
still match — the check that catches a rewrite pass emitting a
shape-inconsistent instruction *before* it dies as an opaque error deep
inside the jitted replay.

Checked invariants (codes in diagnostics.CODES):

- PTL001 every primitive name resolves in ``dispatch.PRIMITIVES``
  (``__gradients__`` is the one structural pseudo-op);
- PTL002 every input vid is defined before use (feed, const, or an
  earlier instruction's output);
- PTL003/PTL004 out_vids are fresh (no redefinition) and were actually
  allocated by this program's vid counter;
- PTL005 feed placeholder vids never overlap the constant pool;
- PTL006 static attrs are hashable (the executable cache keys on them);
- PTL007 ``__gradients__`` is well-formed: >= 2 operands (loss + wrts),
  an int ``fwd_len`` attr no larger than its own position, and one
  output per wrt operand;
- PTL008/PTL009/PTL010 the InferMeta audit.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ...core import dispatch
from .diagnostics import DiagnosticReport, Severity

__all__ = [
    "verify_program", "check_program", "recorded_avals", "propagate_avals",
    "GRAD_OP",
]

GRAD_OP = "__gradients__"

Aval = Tuple[Tuple[int, ...], np.dtype]


def _aval_of(obj) -> Optional[Aval]:
    if isinstance(obj, jax.ShapeDtypeStruct):
        return tuple(obj.shape), np.dtype(obj.dtype)
    try:
        a = np.asarray(obj)
    except Exception:
        return None
    return tuple(a.shape), a.dtype


def recorded_avals(program) -> Dict[int, Aval]:
    """vid -> (shape, dtype) as recorded at capture time.

    Capture pins every produced value (placeholder specs, const arrays,
    eval_shape outputs) in ``_keepalive`` and maps it through
    ``_vid_by_obj``; deserialized programs only carry consts and the
    placeholder decls, so the map is best-effort — the audit compares
    only where a record exists."""
    from ...core.dtype import convert_dtype

    out: Dict[int, Aval] = {}
    for _name, vid, shape, dtype in program._placeholders:
        # same capture rule as Program.add_placeholder: dynamic dims -> 1
        cap = tuple(1 if s in (None, -1) else int(s) for s in shape)
        try:
            out[vid] = (cap, np.dtype(convert_dtype(dtype)))
        except TypeError:
            pass
    vid_by_obj = getattr(program, "_vid_by_obj", {})
    for obj in getattr(program, "_keepalive", ()):
        vid = vid_by_obj.get(id(obj))
        if vid is None:
            continue
        aval = _aval_of(obj)
        if aval is not None:
            out[vid] = aval
    for vid, const in program._consts.items():
        aval = _aval_of(const)
        if aval is not None:
            out.setdefault(vid, aval)
    return out


def _sds(aval: Aval) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(aval[0], aval[1])


@functools.lru_cache(maxsize=8192)
def _cached_eval_shape(prim_name: str, in_avals: Tuple[Aval, ...],
                       static_items) -> Tuple[Optional[Aval], ...]:
    """Shape-inference cache: PassManager(verify=True) re-audits a mostly
    unchanged program after every pass, so keying on (op, operand avals,
    attrs) turns the repeated jax tracing into dict hits — the same
    signature the executable cache (dispatch._jitted_forward) keys on."""
    outs = dispatch.eval_shape(
        prim_name, [_sds(a) for a in in_avals], dict(static_items))
    outs = outs if isinstance(outs, tuple) else (outs,)
    return tuple(_aval_of(o) for o in outs)


def _infer_out_avals(prim_name, in_avals, static_items):
    try:
        return _cached_eval_shape(prim_name, tuple(in_avals), static_items)
    except TypeError:
        # unhashable attrs (separately reported as PTL006): trace uncached
        outs = dispatch.eval_shape(
            prim_name, [_sds(a) for a in in_avals], dict(static_items))
        outs = outs if isinstance(outs, tuple) else (outs,)
        return tuple(_aval_of(o) for o in outs)


def _fmt(aval: Optional[Aval]) -> str:
    if aval is None:
        return "?"
    shape, dtype = aval
    return f"{np.dtype(dtype).name}[{','.join(map(str, shape))}]"


def propagate_avals(program) -> Dict[int, Aval]:
    """Best-effort vid -> aval map: recorded avals seeded with consts and
    placeholders, then pushed through ``eval_shape`` per instruction.
    Never raises — lints and the IR dump use this for annotation."""
    env = dict(recorded_avals(program))
    for prim_name, in_vids, static_items, out_vids in program._insts:
        if all(v in env for v in out_vids):
            continue
        if prim_name == GRAD_OP:
            for v, w in zip(out_vids, in_vids[1:]):
                if w in env:
                    env.setdefault(v, env[w])
            continue
        if prim_name not in dispatch.PRIMITIVES or \
                not all(v in env for v in in_vids):
            continue
        try:
            outs = _infer_out_avals(prim_name, [env[v] for v in in_vids],
                                    static_items)
        except Exception:
            continue
        for v, aval in zip(out_vids, outs):
            if aval is not None:
                env.setdefault(v, aval)
    return env


def verify_program(program, *, infer_meta: bool = True) -> DiagnosticReport:
    """Walk the instruction list once and report every violated invariant.

    Returns a :class:`DiagnosticReport`; ``report.ok`` is False iff the
    program is structurally broken. Pure read-only — safe to call on any
    program at any time (the PassManager calls it between passes)."""
    report = DiagnosticReport()
    E = Severity.ERROR

    consts = program._consts
    feed_vids = set(program._feed_names.values())
    next_vid = getattr(program, "_next_vid", None)

    overlap = feed_vids & set(consts)
    if overlap:
        report.add(
            "PTL005", E,
            f"feed placeholder vids {sorted(overlap)} are also bound in the "
            f"constant pool; replay would shadow the feed",
            hint="a pass (e.g. constant_folding) must never fold a "
                 "placeholder; rebuild the program or drop the const "
                 "binding")

    defined = set(consts) | feed_vids
    meta_env: Dict[int, Aval] = {}
    recorded = {}
    if infer_meta:
        recorded = recorded_avals(program)
        meta_env = {v: recorded[v] for v in defined if v in recorded}

    for idx, inst in enumerate(program._insts):
        try:
            prim_name, in_vids, static_items, out_vids = inst
        except (TypeError, ValueError):
            report.add(
                "PTL001", E,
                f"malformed instruction record {inst!r} (expected "
                f"(prim, in_vids, static_attrs, out_vids))", op_index=idx)
            continue

        known_prim = prim_name == GRAD_OP or prim_name in dispatch.PRIMITIVES
        if not known_prim:
            report.add(
                "PTL001", E,
                f"unknown primitive {prim_name!r}", op_index=idx,
                hint="register it via ops._helpers.defprim / "
                     "dispatch.register_primitive before building or "
                     "loading the program")
        elif prim_name != GRAD_OP \
                and dispatch.PRIMITIVES[prim_name].forward is None:
            known_prim = False  # backward-only prim: nothing to replay
            report.add(
                "PTL001", E,
                f"primitive {prim_name!r} is a backward-only registration "
                f"(forward=None) and cannot appear as a program "
                f"instruction", op_index=idx)

        try:
            hash(tuple(static_items))
        except TypeError:
            report.add(
                "PTL006", E,
                f"static attrs {static_items!r} of {prim_name!r} are "
                f"unhashable", op_index=idx,
                hint="convert lists/dicts/arrays in attrs to tuples — the "
                     "per-signature executable cache keys on them")

        operands_ok = True
        for v in in_vids:
            if v not in defined:
                operands_ok = False
                never = (next_vid is not None and v >= next_vid)
                kind = ("was never allocated by this program" if never
                        else "is used before its definition")
                report.add(
                    "PTL002", E,
                    f"input vid %{v} of {prim_name!r} {kind}", op_index=idx,
                    hint="a rewrite pass dropped or reordered the producing "
                         "instruction; run PassManager(verify=True) to "
                         "catch the offending pass")

        if prim_name == GRAD_OP:
            try:
                attrs = dict(static_items)
            except (TypeError, ValueError):
                attrs = {}
            fwd_len = attrs.get("fwd_len")
            if len(in_vids) < 2:
                report.add(
                    "PTL007", E,
                    f"__gradients__ needs (loss, wrt...) operands, got "
                    f"{len(in_vids)}", op_index=idx)
            elif len(out_vids) != len(in_vids) - 1:
                report.add(
                    "PTL007", E,
                    f"__gradients__ emits {len(out_vids)} grads for "
                    f"{len(in_vids) - 1} wrt operands", op_index=idx)
            if not isinstance(fwd_len, int) or fwd_len < 0:
                # NOTE: fwd_len > idx is legal — rewrite passes shrink the
                # list and the replay uses the instruction's own position
                # (see Executor._compile), so only type/sign are invariant
                report.add(
                    "PTL007", E,
                    f"__gradients__ needs a non-negative int 'fwd_len' "
                    f"attr (got {fwd_len!r})", op_index=idx)
            if not operands_ok:
                report.add(
                    "PTL007", E,
                    "__gradients__ placed before its forward slice: the "
                    "loss/wrt operands are not yet defined at this point",
                    op_index=idx,
                    hint="record_gradients appends the grad section after "
                         "the forward; a pass that reorders instructions "
                         "must keep the grad section behind its operands")

        seen_here = set()
        for v in out_vids:
            if next_vid is not None and v >= next_vid:
                report.add(
                    "PTL004", E,
                    f"out vid %{v} of {prim_name!r} was never allocated "
                    f"(next_vid={next_vid})", op_index=idx,
                    hint="allocate result ids with Program._new_vid() — "
                         "foreign ids break clone() and serialization")
            if v in defined or v in seen_here:
                report.add(
                    "PTL003", E,
                    f"out vid %{v} of {prim_name!r} is already defined "
                    f"(SSA violation)", op_index=idx,
                    hint="each vid has exactly one producer; a fusion pass "
                         "must reuse the *consumer's* out vid and delete "
                         "the producer")
            seen_here.add(v)
        defined.update(out_vids)

        if infer_meta and known_prim and operands_ok:
            _audit_infer_meta(report, idx, prim_name, in_vids, static_items,
                              out_vids, meta_env, recorded)

    return report


def _audit_infer_meta(report, idx, prim_name, in_vids, static_items,
                      out_vids, meta_env: Dict[int, Aval],
                      recorded: Dict[int, Aval]):
    """Re-run shape inference for one instruction and reconcile with the
    capture-time record (the InferMeta/VerifySig audit)."""
    E = Severity.ERROR

    def seed_from_record(vids):
        for v in vids:
            if v in recorded:
                meta_env[v] = recorded[v]

    if not all(v in meta_env for v in in_vids):
        # an upstream audit failure already reported; keep walking with
        # whatever the capture recorded so one bad op yields one error
        seed_from_record(out_vids)
        return

    if prim_name == GRAD_OP:
        for v, w in zip(out_vids, in_vids[1:]):
            meta_env[v] = meta_env[w]
            if v in recorded and recorded[v] != meta_env[v]:
                report.add(
                    "PTL008", E,
                    f"grad of %{w} recorded as {_fmt(recorded[v])} but the "
                    f"wrt value is {_fmt(meta_env[w])}", op_index=idx)
        return

    try:
        outs = _infer_out_avals(prim_name,
                                [meta_env[v] for v in in_vids],
                                static_items)
    except Exception as exc:
        report.add(
            "PTL010", E,
            f"shape inference failed for {prim_name!r}"
            f"({', '.join(_fmt(meta_env[v]) for v in in_vids)}): "
            f"{type(exc).__name__}: {exc}", op_index=idx,
            hint="operand shapes/dtypes or static attrs are inconsistent "
                 "with the primitive's forward")
        seed_from_record(out_vids)
        return

    if len(outs) != len(out_vids):
        report.add(
            "PTL010", E,
            f"{prim_name!r} infers {len(outs)} outputs but the instruction "
            f"records {len(out_vids)} out vids", op_index=idx)
        seed_from_record(out_vids)
        return

    for v, inferred in zip(out_vids, outs):
        if inferred is None:  # non-array output leaf: keep the record
            if v in recorded:
                meta_env[v] = recorded[v]
            continue
        meta_env[v] = inferred
        rec = recorded.get(v)
        if rec is None or inferred is None:
            continue
        if rec[0] != inferred[0]:
            report.add(
                "PTL008", E,
                f"out vid %{v} of {prim_name!r} recorded as {_fmt(rec)} but "
                f"eval_shape infers {_fmt(inferred)}", op_index=idx,
                hint="a pass swapped/rewired out_vids or changed operands "
                     "without re-running shape inference")
        elif np.dtype(rec[1]) != np.dtype(inferred[1]):
            report.add(
                "PTL009", E,
                f"out vid %{v} of {prim_name!r} recorded as {_fmt(rec)} but "
                f"eval_shape infers {_fmt(inferred)}", op_index=idx)


def check_program(program, *, infer_meta: bool = True,
                  context: Optional[str] = None) -> DiagnosticReport:
    """verify_program + raise :class:`ProgramVerificationError` on errors."""
    report = verify_program(program, infer_meta=infer_meta)
    report.raise_if_errors(context=context)
    return report
