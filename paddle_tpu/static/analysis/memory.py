"""Liveness-interval peak-memory estimator over the captured Program.

ROADMAP item 5 (full-depth 8B) starts with *knowing peak HBM before
compile* — today the repo only learns it after the fact from the PR 5
``device.hbm_watermark_bytes`` gauge. This module predicts it
statically: value footprints come from the same best-effort aval map
the lints use (``verify.propagate_avals``), live intervals from the
SHARED liveness sweep (``liveness.live_op_indices`` — the same roots
the dead-op lint and the DCE passes agree on), and the walk replays
the allocator's life: consts (parameters) resident for the whole
program, feeds resident from entry, each live op's outputs allocated
before its operands can die, operands freed after their LAST live use,
fetch targets never freed.

The ``__gradients__`` pseudo-op models jax.grad's residual policy: the
outputs of every forward op live w.r.t. the loss are HELD until the
gradient instruction (activations saved for the backward), and the
gradient outputs allocate there — without this the estimate misses the
term that actually decides whether a training step fits.

Sharding-aware: pass ``placements`` (vid -> DistTensorSpec, e.g. from
``auto_parallel.completion.complete_placements``) and every footprint
divides by its shard count — the estimate becomes per-chip, which is
the number the PTL301 budget check compares against the device limit.

**PTL301** (:func:`lint_memory_budget`) is the predicted-OOM-before-
compile diagnostic: peak estimate vs device budget (explicit argument,
``PADDLE_TPU_HBM_LIMIT_BYTES`` env, or the PJRT allocator's
``bytes_limit``), fired from ``Executor.run`` on the pre-compile path
— a loud answer *seconds* before XLA would spend minutes compiling a
program that cannot fit.

Validation: ``tests/test_cost_analysis.py`` pins the estimator against
a step-by-step allocation simulator on the seeded generated programs
(exact agreement) and against the measured watermark on the bench
llama train program (tolerance band).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ... import observability as _obs
from .cost import (M_ESTIMATE_SECONDS, M_PREDICTED_OOM, M_PREDICTED_PEAK,
                   _nbytes, _resolve_fetch_vids, _shard_divisor,
                   executed_op_indices)
from .diagnostics import DiagnosticReport, Severity
from .liveness import live_op_indices
from .verify import GRAD_OP, propagate_avals

__all__ = ["MemoryEstimate", "estimate_peak_memory", "lint_memory_budget",
           "device_memory_budget", "HBM_LIMIT_ENV", "OOM_CHECK_ENV"]

#: explicit per-chip memory budget override (bytes) for the PTL301
#: check — wins over the PJRT allocator's reported bytes_limit.
HBM_LIMIT_ENV = "PADDLE_TPU_HBM_LIMIT_BYTES"

#: Executor.run pre-compile behavior when the estimate exceeds the
#: budget: "warn" (default — loud diagnostic + metric, compile
#: proceeds), "raise" (refuse before compile), "off".
OOM_CHECK_ENV = "PADDLE_TPU_OOM_CHECK"


@dataclass
class MemoryEstimate:
    """Peak + breakdown of one program replay's resident memory."""

    peak_bytes: int = 0
    peak_op_index: Optional[int] = None
    const_bytes: int = 0
    feed_bytes: int = 0
    fetch_bytes: int = 0
    #: resident bytes after each instruction (dead ops repeat the
    #: previous value) — the allocation timeline a test can replay
    timeline: List[int] = field(default_factory=list)
    unknown_vids: int = 0

    def render(self) -> str:
        at = f" at op#{self.peak_op_index}" if self.peak_op_index \
            is not None else ""
        return (f"peak {self.peak_bytes:,}B{at} (consts "
                f"{self.const_bytes:,}B + feeds {self.feed_bytes:,}B "
                f"resident; fetch {self.fetch_bytes:,}B held at exit; "
                f"{self.unknown_vids} vid(s) without avals)")


def estimate_peak_memory(program, fetch=None, *, placements=None,
                         avals=None) -> MemoryEstimate:
    """Liveness-interval peak-memory estimate of one replay.

    ``fetch`` (Tensors or vids; falls back to the recorded
    ``_fetch_vids``) roots liveness; without roots every op is treated
    as live (conservative). ``placements`` divides each value's
    footprint by its shard count for a per-chip estimate."""
    with _obs.span("cost.estimate_peak_memory",
                   histogram=M_ESTIMATE_SECONDS,
                   hist_labels={"kind": "memory"}):
        return _estimate(program, fetch, placements, avals)


def _estimate(program, fetch, placements, avals) -> MemoryEstimate:
    avals = avals if avals is not None else propagate_avals(program)
    placements = placements or {}
    fetch_vids = set(_resolve_fetch_vids(program, fetch))
    insts = list(program._insts)
    kept = executed_op_indices(insts, fetch_vids) if fetch_vids \
        else set(range(len(insts)))

    est = MemoryEstimate()
    _bytes_cache: Dict[int, int] = {}

    def bytes_of(vid) -> int:
        b = _bytes_cache.get(vid)
        if b is None:
            a = avals.get(vid)
            if a is None:
                est.unknown_vids += 1  # memoized: counted once per vid
                b = 0
            else:
                b = _nbytes(a) // _shard_divisor(placements.get(vid))
            _bytes_cache[vid] = b
        return b

    const_vids = set(program._consts)
    feed_vids = set(program._feed_names.values())
    est.const_bytes = sum(bytes_of(v) for v in const_vids)
    est.feed_bytes = sum(bytes_of(v) for v in feed_vids)

    # last live use per vid: seeded at the defining op (an output never
    # consumed dies where it is produced), extended by every consuming
    # op, and extended to the grad instruction for backward residuals.
    # Fetch targets, consts and feeds never enter the expiry map.
    last_use: Dict[int, int] = {}
    for idx in kept:
        for v in insts[idx][3]:
            last_use.setdefault(v, idx)
        for v in insts[idx][1]:
            last_use[v] = max(last_use.get(v, idx), idx)
    for g in (i for i in kept if insts[i][0] == GRAD_OP):
        # residuals: outputs of forward ops live w.r.t. the loss are
        # saved for the backward — hold them until the grad instruction
        loss_vid = insts[g][1][0] if insts[g][1] else None
        if loss_vid is None:
            continue
        for i in live_op_indices(insts[:g], (loss_vid,)):
            for v in insts[i][3]:
                last_use[v] = max(last_use.get(v, g), g)
    expiry: Dict[int, list] = {}
    for v, idx in last_use.items():
        if v not in fetch_vids and v not in const_vids \
                and v not in feed_vids:
            expiry.setdefault(idx, []).append(v)

    resident = est.const_bytes + est.feed_bytes
    live_bytes: Dict[int, int] = {}  # non-const/feed values currently held
    peak, peak_idx = resident, None
    for idx, (prim_name, in_vids, _static, out_vids) in enumerate(insts):
        if idx not in kept:
            est.timeline.append(resident)
            continue
        # outputs allocate while operands are still held (both buffers
        # exist during the op's execution)
        for v in out_vids:
            if v not in live_bytes and v not in const_vids \
                    and v not in feed_vids:
                b = bytes_of(v)
                live_bytes[v] = b
                resident += b
        if resident > peak:
            peak, peak_idx = resident, idx
        # everything whose last live use is this op dies now — operand,
        # never-consumed output, or a backward residual expiring at the
        # grad instruction without being one of its operands
        for v in expiry.get(idx, ()):
            if v in live_bytes:
                resident -= live_bytes.pop(v)
        est.timeline.append(resident)

    est.peak_bytes = peak
    est.peak_op_index = peak_idx
    est.fetch_bytes = sum(bytes_of(v) for v in fetch_vids)
    return est


def device_memory_budget() -> int:
    """Per-chip memory budget for the PTL301 check: the
    ``PADDLE_TPU_HBM_LIMIT_BYTES`` override when set, else the PJRT
    allocator's reported ``bytes_limit`` (0 on platforms that report
    none — CPU — which disables the check)."""
    env = os.environ.get(HBM_LIMIT_ENV)
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    try:
        from ...device import memory as dev_mem

        return int(dev_mem.memory_stats().get("bytes_limit", 0))
    except Exception:
        return 0


def lint_memory_budget(program, fetch=None, *, limit_bytes=None,
                       placements=None, name: str = "program",
                       estimate: Optional[MemoryEstimate] = None
                       ) -> DiagnosticReport:
    """**PTL301**: predicted OOM before compile.

    Compares the liveness peak estimate against ``limit_bytes``
    (default: :func:`device_memory_budget`); a limit of 0 means no
    budget is known and the report comes back empty. Records the
    prediction in ``cost.predicted_peak_hbm_bytes`` and counts firings
    in ``cost.predicted_oom``."""
    report = DiagnosticReport()
    limit = device_memory_budget() if limit_bytes is None \
        else int(limit_bytes)
    if limit <= 0:
        return report
    est = estimate if estimate is not None else \
        estimate_peak_memory(program, fetch, placements=placements)
    if _obs.state.on:
        M_PREDICTED_PEAK.set(int(est.peak_bytes), name=name)
    if est.peak_bytes > limit:
        if _obs.state.on:
            M_PREDICTED_OOM.inc(name=name)
            _obs.emit("cost.predicted_oom", name=name,
                      peak_bytes=int(est.peak_bytes), limit_bytes=limit,
                      peak_op_index=est.peak_op_index)
        report.add(
            "PTL301", Severity.ERROR,
            f"predicted peak memory {est.peak_bytes:,}B exceeds the "
            f"device budget {limit:,}B "
            f"({est.peak_bytes / limit:.2f}x) — this program is "
            f"expected to OOM before XLA even finishes compiling it",
            op_index=est.peak_op_index,
            hint="shrink the batch/sequence, shard more ways (pass the "
                 "placement plan for a per-chip estimate), enable "
                 "recompute checkpoints, or raise "
                 f"{HBM_LIMIT_ENV} if the budget is wrong; set "
                 f"{OOM_CHECK_ENV}=off to silence the pre-compile "
                 "check")
    return report
