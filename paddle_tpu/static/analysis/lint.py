"""Lint framework over the captured static ``Program``.

Reference: the inference analysis pipeline's read-only passes
(paddle/fluid/inference/analysis/ — each AnalysisPass inspects the graph
and annotates it before any rewrite runs). Lints here are *advisory*:
the program is structurally valid (run ``verify.verify_program`` first
for that) but contains something a rewrite pass could fix or a user
should know about — dead ops, unused feeds, redundant cast/transpose
chains, CSE candidates, silent fp64->fp32 demotion, non-jittable
primitives in a jit-replayed program.

Each lint is a registered function ``fn(ctx) -> iterable[finding]``
keyed by a ``PTL1xx`` code; ``run_lints`` assembles one shared
:class:`LintContext` (consumer map, best-effort avals, fetch/feed vids)
and funnels every finding into a :class:`DiagnosticReport`.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

from ...core import dispatch
from .diagnostics import DiagnosticReport, Severity
from .verify import GRAD_OP, propagate_avals

__all__ = ["LintContext", "run_lints", "register_lint", "LINTS"]

# prims whose value depends on RNG/state: never CSE/DCE candidates
_EFFECTFUL_MARKERS = ("rand", "uniform", "normal", "dropout", "bernoulli",
                      "poisson", "multinomial", "exponential", "seed",
                      "print", "py_func", "barrier")


def _effectful(prim_name: str) -> bool:
    low = prim_name.lower()
    return any(m in low for m in _EFFECTFUL_MARKERS)


def _attrs_dict(static_items) -> Dict:
    """Static attrs as a dict, {} when malformed (the verifier reports
    malformed attrs; lints must keep walking)."""
    try:
        return dict(static_items)
    except (TypeError, ValueError):
        return {}


class LintContext:
    """Shared read-only view of one program, built once per run."""

    def __init__(self, program, fetch_vids: Optional[Iterable[int]] = None):
        self.program = program
        self.insts: List[tuple] = list(program._insts)
        self.avals = propagate_avals(program)
        self.feed_vids: Dict[int, str] = {
            vid: name for name, vid in program._feed_names.items()}
        if fetch_vids is None:
            fetch_vids = getattr(program, "_fetch_vids", ()) or ()
        self.fetch_vids: Set[int] = set(fetch_vids)
        self.producer: Dict[int, int] = {}
        self.consumers: Dict[int, List[int]] = {}
        for idx, (_n, in_vids, _s, out_vids) in enumerate(self.insts):
            for v in in_vids:
                self.consumers.setdefault(v, []).append(idx)
            for v in out_vids:
                self.producer.setdefault(v, idx)

    def dtype_of(self, vid) -> Optional[np.dtype]:
        aval = self.avals.get(vid)
        return None if aval is None else np.dtype(aval[1])


LINTS: List[Tuple[str, Callable]] = []


def register_lint(code: str):
    """Register ``fn(ctx) -> iterable[(message, op_index, hint)]``."""

    def deco(fn):
        LINTS.append((code, fn))
        return fn

    return deco


def run_lints(program, fetch=None, *,
              codes: Optional[Iterable[str]] = None) -> DiagnosticReport:
    """Run every registered lint (or the subset in ``codes``).

    ``fetch`` takes Tensors or vids and enables the liveness-based lints;
    without it (and without a recorded ``_fetch_vids``) dead-op/unused-feed
    findings are skipped rather than guessed."""
    fetch_vids = None
    if fetch is not None:
        fetch_vids = [v if isinstance(v, int) else program.vid_of(v)
                      for v in fetch]
    ctx = LintContext(program, fetch_vids)
    only = set(codes) if codes is not None else None
    report = DiagnosticReport()
    for code, fn in LINTS:
        if only is not None and code not in only:
            continue
        for message, op_index, hint in fn(ctx):
            report.add(code, Severity.WARNING, message,
                       op_index=op_index, hint=hint)
    return report


# ---------------------------------------------------------------------------
# built-in lints
# ---------------------------------------------------------------------------
@register_lint("PTL101")
def _dead_ops(ctx: LintContext):
    """Ops whose outputs never (transitively) reach a fetch target."""
    if not ctx.fetch_vids:
        return
    live: Set[int] = set(ctx.fetch_vids)
    kept: Set[int] = set()
    for idx in range(len(ctx.insts) - 1, -1, -1):
        prim_name, in_vids, _s, out_vids = ctx.insts[idx]
        if any(v in live for v in out_vids) or _effectful(prim_name) \
                or prim_name == GRAD_OP:
            kept.add(idx)
            live.update(in_vids)
    for idx, (prim_name, _i, _s, out_vids) in enumerate(ctx.insts):
        if idx not in kept:
            yield (f"{prim_name!r} (outs {sorted(out_vids)}) never reaches "
                   f"a fetch target", idx,
                   "run the dead_code_elimination pass, or fetch the value")


@register_lint("PTL102")
def _unused_feeds(ctx: LintContext):
    for vid, name in sorted(ctx.feed_vids.items()):
        if not ctx.consumers.get(vid) and vid not in ctx.fetch_vids:
            yield (f"feed {name!r} (%{vid}) is declared but never consumed",
                   None,
                   "drop the static.data declaration or stop requiring the "
                   "feed at Executor.run")


@register_lint("PTL103")
def _redundant_casts(ctx: LintContext):
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name != "cast_p" or not in_vids:
            continue
        src = ctx.dtype_of(in_vids[0])
        dst = ctx.dtype_of(out_vids[0]) if out_vids else None
        if src is not None and dst is not None and src == dst:
            yield (f"cast of %{in_vids[0]} to {dst.name} is a no-op "
                   f"(operand is already {src.name})", idx,
                   "delete the cast; it costs a copy outside fusion")
            continue
        prod = ctx.producer.get(in_vids[0])
        if prod is not None and ctx.insts[prod][0] == "cast_p":
            inner_src = ctx.dtype_of(ctx.insts[prod][1][0])
            src_s = inner_src.name if inner_src is not None else "?"
            dst_s = dst.name if dst is not None else "?"
            yield (f"cast chain %{ctx.insts[prod][1][0]} -> %{in_vids[0]} "
                   f"-> %{out_vids[0] if out_vids else '?'} "
                   f"({src_s} -> ... -> {dst_s})", idx,
                   "collapse to a single cast from the original dtype "
                   "(beware: a narrowing intermediate changes numerics)")


@register_lint("PTL104")
def _redundant_transposes(ctx: LintContext):
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name != "transpose_p" or not in_vids:
            continue
        perm = _attrs_dict(static_items).get("perm")
        if perm is not None and list(perm) == sorted(range(len(perm))):
            yield (f"transpose of %{in_vids[0]} with identity perm "
                   f"{tuple(perm)}", idx, "delete the transpose")
            continue
        prod = ctx.producer.get(in_vids[0])
        if prod is None or ctx.insts[prod][0] != "transpose_p":
            continue
        inner = _attrs_dict(ctx.insts[prod][2]).get("perm")
        if inner is None or perm is None or len(inner) != len(perm):
            continue
        composed = [inner[p] for p in perm]
        if composed == sorted(range(len(composed))):
            yield (f"transpose chain op#{prod} -> op#{idx} composes to the "
                   f"identity permutation", idx,
                   "delete both transposes (the chain is a no-op)")


@register_lint("PTL105")
def _cse_candidates(ctx: LintContext):
    seen: Dict[tuple, int] = {}
    for idx, (prim_name, in_vids, static_items, _o) in enumerate(ctx.insts):
        if prim_name == GRAD_OP or not in_vids or _effectful(prim_name):
            continue
        try:
            key = (prim_name, tuple(in_vids), tuple(static_items))
            hash(key)
        except TypeError:
            continue
        first = seen.setdefault(key, idx)
        if first != idx:
            yield (f"{prim_name!r} over vids {tuple(in_vids)} recomputes "
                   f"op#{first} with identical operands and attrs", idx,
                   "reuse op#%d's outputs (classic CSE); XLA dedups inside "
                   "one jit but not across cache entries" % first)


@register_lint("PTL106")
def _silent_fp64_demotion(ctx: LintContext):
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name == GRAD_OP:
            continue
        if prim_name == "cast_p":
            # an explicit cast to float32 is a *requested* demotion, not a
            # silent one
            target = _attrs_dict(static_items).get("dtype")
            if target is not None and np.dtype(target) == np.dtype(
                    "float32"):
                continue
        in_dts = [ctx.dtype_of(v) for v in in_vids]
        out_dts = [ctx.dtype_of(v) for v in out_vids]
        if not in_dts or not out_dts:
            continue
        if any(dt == np.dtype("float64") for dt in in_dts if dt is not None) \
                and all(dt == np.dtype("float32")
                        for dt in out_dts if dt is not None) \
                and any(dt is not None for dt in out_dts):
            yield (f"{prim_name!r} consumes float64 but emits float32 — "
                   f"double precision is silently lost", idx,
                   "the op's forward narrows internally; cast the operand "
                   "to float32 explicitly if the demotion is intended, or "
                   "keep the math in float64")


@register_lint("PTL107")
def _non_jittable_under_jit(ctx: LintContext):
    for idx, (prim_name, _i, _s, _o) in enumerate(ctx.insts):
        prim = dispatch.PRIMITIVES.get(prim_name)
        if prim is not None and not prim.jittable:
            yield (f"{prim_name!r} is marked non-jittable but Executor.run "
                   f"replays the whole program under jax.jit", idx,
                   "host callbacks/impure ops must go through "
                   "jax.pure_callback (or run eagerly outside the program)")
