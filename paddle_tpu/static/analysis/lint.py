"""Lint framework over the captured static ``Program``.

Reference: the inference analysis pipeline's read-only passes
(paddle/fluid/inference/analysis/ — each AnalysisPass inspects the graph
and annotates it before any rewrite runs). Lints here are *advisory*:
the program is structurally valid (run ``verify.verify_program`` first
for that) but contains something a rewrite pass could fix or a user
should know about — dead ops, unused feeds, redundant cast/transpose
chains, CSE candidates, silent fp64->fp32 demotion, non-jittable
primitives in a jit-replayed program.

Each lint is a registered function ``fn(ctx) -> iterable[finding]``
keyed by a ``PTL1xx`` code; ``run_lints`` assembles one shared
:class:`LintContext` (consumer map, best-effort avals, fetch/feed vids)
and funnels every finding into a :class:`DiagnosticReport`.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

from ...core import dispatch
from .diagnostics import DiagnosticReport, Severity
from .liveness import is_effectful as _effectful
from .liveness import live_op_indices
from .verify import GRAD_OP, propagate_avals

__all__ = ["LintContext", "run_lints", "register_lint", "LINTS",
           "lossless_cast"]


def _attrs_dict(static_items) -> Dict:
    """Static attrs as a dict, {} when malformed (the verifier reports
    malformed attrs; lints must keep walking)."""
    try:
        return dict(static_items)
    except (TypeError, ValueError):
        return {}


class LintContext:
    """Shared read-only view of one program, built once per run."""

    def __init__(self, program, fetch_vids: Optional[Iterable[int]] = None):
        self.program = program
        self.insts: List[tuple] = list(program._insts)
        self.avals = propagate_avals(program)
        self.feed_vids: Dict[int, str] = {
            vid: name for name, vid in program._feed_names.items()}
        if fetch_vids is None:
            fetch_vids = getattr(program, "_fetch_vids", ()) or ()
        self.fetch_vids: Set[int] = set(fetch_vids)
        self.producer: Dict[int, int] = {}
        self.consumers: Dict[int, List[int]] = {}
        for idx, (_n, in_vids, _s, out_vids) in enumerate(self.insts):
            for v in in_vids:
                self.consumers.setdefault(v, []).append(idx)
            for v in out_vids:
                self.producer.setdefault(v, idx)

    def dtype_of(self, vid) -> Optional[np.dtype]:
        aval = self.avals.get(vid)
        return None if aval is None else np.dtype(aval[1])


LINTS: List[Tuple[str, Severity, Callable]] = []


def register_lint(code: str, severity: Severity = Severity.WARNING):
    """Register ``fn(ctx) -> iterable[(message, op_index, hint)]``."""

    def deco(fn):
        LINTS.append((code, severity, fn))
        return fn

    return deco


def run_lints(program, fetch=None, *,
              codes: Optional[Iterable[str]] = None) -> DiagnosticReport:
    """Run every registered lint (or the subset in ``codes``).

    ``fetch`` takes Tensors or vids and enables the liveness-based lints;
    without it (and without a recorded ``_fetch_vids``) dead-op/unused-feed
    findings are skipped rather than guessed."""
    fetch_vids = None
    if fetch is not None:
        fetch_vids = [v if isinstance(v, int) else program.vid_of(v)
                      for v in fetch]
    ctx = LintContext(program, fetch_vids)
    only = set(codes) if codes is not None else None
    report = DiagnosticReport()
    for code, severity, fn in LINTS:
        if only is not None and code not in only:
            continue
        for message, op_index, hint in fn(ctx):
            report.add(code, severity, message,
                       op_index=op_index, hint=hint)
    return report


# ---------------------------------------------------------------------------
# built-in lints
# ---------------------------------------------------------------------------
@register_lint("PTL101")
def _dead_ops(ctx: LintContext):
    """Ops whose outputs never (transitively) reach a fetch target.

    Reachability comes from the SHARED sweep in liveness.py — the same
    one the dead-code rewrite passes delete against, so this lint and
    those passes agree on deadness by construction."""
    if not ctx.fetch_vids:
        return
    kept = live_op_indices(ctx.insts, ctx.fetch_vids)
    for idx, (prim_name, _i, _s, out_vids) in enumerate(ctx.insts):
        if idx not in kept:
            yield (f"{prim_name!r} (outs {sorted(out_vids)}) never reaches "
                   f"a fetch target", idx,
                   "run the dead_code_elimination pass, or fetch the value")


@register_lint("PTL102")
def _unused_feeds(ctx: LintContext):
    for vid, name in sorted(ctx.feed_vids.items()):
        if not ctx.consumers.get(vid) and vid not in ctx.fetch_vids:
            yield (f"feed {name!r} (%{vid}) is declared but never consumed",
                   None,
                   "drop the static.data declaration or stop requiring the "
                   "feed at Executor.run")


def lossless_cast(src, mid) -> bool:
    """True when casting ``src`` -> ``mid`` preserves every value, i.e.
    a ``src -> mid -> dst`` chain computes the same result as a single
    ``src -> dst`` cast. int -> float is decided by mantissa coverage
    BEFORE consulting numpy's table: ``can_cast(int64, float64,
    'safe')`` is True there even though float64 only holds integers up
    to 2**53 exactly. The finfo/iinfo fallbacks cover the ml_dtypes
    extension floats (bfloat16, fp8) numpy's table does not know.
    Unknown pairs read as lossy — a wrong False only suppresses a
    rewrite, never changes numerics."""
    src, mid = np.dtype(src), np.dtype(mid)
    if src == mid:
        return True
    if src.kind in "iu" and mid.kind in "fc":
        try:  # exact iff the float mantissa covers every int value
            value_bits = 8 * src.itemsize - (1 if src.kind == "i" else 0)
            return np.finfo(mid).nmant + 1 >= value_bits
        except (TypeError, ValueError):
            return False
    try:
        if np.can_cast(src, mid, casting="safe"):
            return True
    except TypeError:
        pass
    try:  # float -> float beyond numpy's table (bfloat16 et al.)
        fs, fm = np.finfo(src), np.finfo(mid)
        return (fm.nmant >= fs.nmant and fm.maxexp >= fs.maxexp
                and fm.minexp <= fs.minexp)
    except (TypeError, ValueError):
        pass
    try:  # int -> int
        is_, im = np.iinfo(src), np.iinfo(mid)
        return im.min <= is_.min and im.max >= is_.max
    except (TypeError, ValueError):
        return False


def _cast_chain(ctx: LintContext, idx: int):
    """(orig_vid, orig_dtype, mid_dtype, dst_dtype) when op#idx is the
    outer cast of a cast-of-cast chain with known dtypes, else None."""
    prim_name, in_vids, _static, out_vids = ctx.insts[idx]
    if prim_name != "cast_p" or not in_vids or not out_vids:
        return None
    prod = ctx.producer.get(in_vids[0])
    if prod is None or ctx.insts[prod][0] != "cast_p" \
            or not ctx.insts[prod][1]:
        return None
    orig_vid = ctx.insts[prod][1][0]
    orig = ctx.dtype_of(orig_vid)
    mid = ctx.dtype_of(in_vids[0])
    dst = ctx.dtype_of(out_vids[0])
    if orig is None or mid is None or dst is None:
        return None
    return orig_vid, orig, mid, dst


@register_lint("PTL103")
def _redundant_casts(ctx: LintContext):
    """No-op casts and LOSSLESSLY collapsible cast chains. A chain whose
    intermediate narrows the dtype is NOT redundant (collapsing it
    changes numerics) — those are reported separately as PTL108."""
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name != "cast_p" or not in_vids:
            continue
        src = ctx.dtype_of(in_vids[0])
        dst = ctx.dtype_of(out_vids[0]) if out_vids else None
        if src is not None and dst is not None and src == dst:
            yield (f"cast of %{in_vids[0]} to {dst.name} is a no-op "
                   f"(operand is already {src.name})", idx,
                   "delete the cast; it costs a copy outside fusion")
            continue
        chain = _cast_chain(ctx, idx)
        if chain is None:
            continue
        orig_vid, orig, mid, dst_d = chain
        if lossless_cast(orig, mid):
            yield (f"cast chain %{orig_vid} -> %{in_vids[0]} "
                   f"-> %{out_vids[0]} ({orig.name} -> {mid.name} -> "
                   f"{dst_d.name}; intermediate is lossless)", idx,
                   "collapse to a single cast from the original dtype")


@register_lint("PTL108", Severity.NOTE)
def _narrowing_cast_chains(ctx: LintContext):
    """Cast chains whose intermediate NARROWS the dtype: the round trip
    changes numerics (that may well be intended — e.g. a precision
    fence), so unlike PTL103 this is a note, never a rewrite target."""
    for idx in range(len(ctx.insts)):
        chain = _cast_chain(ctx, idx)
        if chain is None:
            continue
        orig_vid, orig, mid, dst = chain
        if not lossless_cast(orig, mid):
            yield (f"cast chain %{orig_vid} ({orig.name}) -> {mid.name} "
                   f"-> {dst.name} narrows through {mid.name}: the "
                   f"intermediate changes numerics, the chain is not "
                   f"redundant", idx,
                   "nothing to collapse — if the precision fence is "
                   "unintended, cast once from the source dtype")


@register_lint("PTL104")
def _redundant_transposes(ctx: LintContext):
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name != "transpose_p" or not in_vids:
            continue
        perm = _attrs_dict(static_items).get("perm")
        if perm is not None and list(perm) == sorted(range(len(perm))):
            yield (f"transpose of %{in_vids[0]} with identity perm "
                   f"{tuple(perm)}", idx, "delete the transpose")
            continue
        prod = ctx.producer.get(in_vids[0])
        if prod is None or ctx.insts[prod][0] != "transpose_p":
            continue
        inner = _attrs_dict(ctx.insts[prod][2]).get("perm")
        if inner is None or perm is None or len(inner) != len(perm):
            continue
        composed = [inner[p] for p in perm]
        if composed == sorted(range(len(composed))):
            yield (f"transpose chain op#{prod} -> op#{idx} composes to the "
                   f"identity permutation", idx,
                   "delete both transposes (the chain is a no-op)")
        else:
            # two data movements where one suffices: any transpose chain
            # composes to a SINGLE transpose with the composed perm
            yield (f"transpose chain op#{prod} -> op#{idx} composes to a "
                   f"single transpose with perm {tuple(composed)}", idx,
                   "replace the pair with one transpose of the original "
                   "operand using the composed permutation")


@register_lint("PTL105")
def _cse_candidates(ctx: LintContext):
    seen: Dict[tuple, int] = {}
    for idx, (prim_name, in_vids, static_items, _o) in enumerate(ctx.insts):
        if prim_name == GRAD_OP or not in_vids or _effectful(prim_name):
            continue
        try:
            key = (prim_name, tuple(in_vids), tuple(static_items))
            hash(key)
        except TypeError:
            continue
        first = seen.setdefault(key, idx)
        if first != idx:
            yield (f"{prim_name!r} over vids {tuple(in_vids)} recomputes "
                   f"op#{first} with identical operands and attrs", idx,
                   "reuse op#%d's outputs (classic CSE); XLA dedups inside "
                   "one jit but not across cache entries" % first)


@register_lint("PTL106")
def _silent_fp64_demotion(ctx: LintContext):
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(ctx.insts):
        if prim_name == GRAD_OP:
            continue
        if prim_name == "cast_p":
            # an explicit cast to float32 is a *requested* demotion, not a
            # silent one
            target = _attrs_dict(static_items).get("dtype")
            if target is not None and np.dtype(target) == np.dtype(
                    "float32"):
                continue
        in_dts = [ctx.dtype_of(v) for v in in_vids]
        out_dts = [ctx.dtype_of(v) for v in out_vids]
        if not in_dts or not out_dts:
            continue
        if any(dt == np.dtype("float64") for dt in in_dts if dt is not None) \
                and all(dt == np.dtype("float32")
                        for dt in out_dts if dt is not None) \
                and any(dt is not None for dt in out_dts):
            yield (f"{prim_name!r} consumes float64 but emits float32 — "
                   f"double precision is silently lost", idx,
                   "the op's forward narrows internally; cast the operand "
                   "to float32 explicitly if the demotion is intended, or "
                   "keep the math in float64")


@register_lint("PTL107")
def _non_jittable_under_jit(ctx: LintContext):
    for idx, (prim_name, _i, _s, _o) in enumerate(ctx.insts):
        prim = dispatch.PRIMITIVES.get(prim_name)
        if prim is not None and not prim.jittable:
            yield (f"{prim_name!r} is marked non-jittable but Executor.run "
                   f"replays the whole program under jax.jit", idx,
                   "host callbacks/impure ops must go through "
                   "jax.pure_callback (or run eagerly outside the program)")


# compute-bound prims where operand dtype decides which MXU path the
# compiler picks — a single fp32 operand upcasts the whole contraction
_HEAVY_MARKERS = ("matmul", "linear", "conv", "sdpa", "attention",
                  "einsum", "bmm", "addmm")


def _heavy(prim_name: str) -> bool:
    low = prim_name.lower()
    return any(m in low for m in _HEAVY_MARKERS)


@register_lint("PTL201")
def _fp32_on_bf16_hot_path(ctx: LintContext):
    """A compute-bound op runs in float32 while (some of) its data is
    bfloat16-precision anyway: type promotion at capture inserts an
    upcast ``cast_p`` when a bf16 tensor meets an fp32 one, so the GEMM
    pays the fp32 MXU rate for operands that never carried more than
    bf16 precision. The first sharding-aware lint family (PTL2xx) —
    dtype is part of the layout the auto-parallel planner schedules
    around. Fix direction: demote the fp32 side (usually a weight left
    out of ``model.bfloat16()``), not the compute."""
    low_prec = (np.dtype("bfloat16"), np.dtype("float16"))
    f32 = np.dtype("float32")
    for idx, (prim_name, in_vids, _static, _out_vids) in \
            enumerate(ctx.insts):
        if not _heavy(prim_name) or len(in_vids) < 2:
            continue
        dts = [(v, ctx.dtype_of(v)) for v in in_vids]
        if not any(d == f32 for _v, d in dts):
            continue
        # mixed direct operands (possible on hand-built programs)
        bf = [v for v, d in dts if d in low_prec]
        # fp32 operands that are upcasts of low-precision data (the
        # shape API-captured programs take: promotion casts first)
        upcast = []
        for v, d in dts:
            if d != f32:
                continue
            prod = ctx.producer.get(v)
            if prod is None or ctx.insts[prod][0] != "cast_p" \
                    or not ctx.insts[prod][1]:
                continue
            src = ctx.dtype_of(ctx.insts[prod][1][0])
            if src in low_prec:
                upcast.append((v, ctx.insts[prod][1][0], src))
        if upcast:
            v, src_v, src = upcast[0]
            yield (f"{prim_name!r} computes in float32 but operand %{v} "
                   f"is an upcast of {src.name} %{src_v} — the op runs "
                   f"at the fp32 rate on a {src.name} hot path", idx,
                   "demote the float32 side to match (e.g. the weight "
                   "missed by model.bfloat16()); the data never carried "
                   "fp32 precision, only the throughput cost remains")
        elif bf:
            fp = [v for v, d in dts if d == f32]
            yield (f"{prim_name!r} mixes {', '.join(f'%{v}' for v in bf)} "
                   f"(low precision) with float32 operands "
                   f"({', '.join(f'%{v}' for v in fp)}) — promotion "
                   f"upcasts the whole op to float32", idx,
                   "cast the float32 operand(s) down (or keep the path "
                   "float32 intentionally); the mixed GEMM runs at the "
                   "fp32 rate, not the bf16 rate")
