"""Lint-to-rewrite driver: iterate the lint-fix pass pipeline to a
fixed point and measure the benefit.

``run_lints`` *detects* dead ops, unused feeds, redundant cast/
transpose chains and CSE candidates (PTL101/102/103/104/105);
the lint-fix rewrite passes in ``distributed/passes/lint_fix_passes.py``
*fix* them, each structured as "run the lint, apply the fix per
finding, re-lint to confirm zero findings". :func:`optimize_program`
closes the loop: it drives the whole pipeline through
``PassManager`` (so the verifier brackets every pass and the
``passes.pass_op_delta``/wall-time series keep recording) and repeats
until an iteration changes nothing — one pass's rewrite is the next
pass's fodder (a collapsed cast chain leaves a dead inner cast for
DCE; a deduped subexpression leaves an unused feed for the pruner).

Measurement rides the ``opt.`` metric subsystem (claimed in
``observability.metrics.CLAIMED_SUBSYSTEMS``):

- ``opt.findings_fixed{code}``   — lint findings eliminated, by code;
- ``opt.findings_remaining{code}`` — findings the final re-lint still
  reports (protected fetch targets, refused narrowing chains);
- ``opt.rewrite_seconds{name}``  — per-pass wall time inside the fix
  loop (recorded by each pass);
- ``opt.fixedpoint_iterations``  — pipeline repetitions until
  quiescence;
- ``opt.runs`` / ``opt.ops_removed`` — driver-level totals.

So pass scheduling can be argued from data (`tools/metrics_report.py`
renders the per-code fixed/remaining table; ``bench.py --metrics``
rolls the totals into the bench record) instead of assumed.

Scheduling IS argued from data now: each fixed-point iteration starts
with ONE lint sweep over the rewrite codes, passes whose code has zero
findings are **skipped** (cost-gated — no lint-fix pass pays its
lint+fix+re-lint wall time to discover it has nothing to do), and the
remaining passes run in **benefit order**: predicted benefit (the
iteration's finding count for the pass's code) divided by observed
cost (the pass's historical mean ``opt.rewrite_seconds`` from the
metrics registry, when recorded). Skips land in
``opt.passes_skipped{name}`` and in the **PTL303** no-benefit report
on the returned :class:`OptimizeResult`; the bit-exact equivalence
harness (tests/test_rewrite_passes.py + test_cost_analysis.py) pins
that re-ordering and skipping never change fetch outputs.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from ... import observability as _obs
from .diagnostics import DiagnosticReport, Severity
from .lint import run_lints

__all__ = ["optimize_program", "OptimizeResult", "REWRITE_CODES",
           "DEFAULT_PIPELINE", "OPTIMIZE_ENV_FLAG"]

#: env switch for the Executor.run pre-compile hook (see
#: static/program.py); FLAGS_optimize_programs is the flag twin.
OPTIMIZE_ENV_FLAG = "PADDLE_TPU_OPTIMIZE"

#: the lint codes the rewrite pipeline fixes — the "zero findings after
#: optimize_program" acceptance set.
REWRITE_CODES = ("PTL101", "PTL102", "PTL103", "PTL104", "PTL105")

#: pass order: structure rewrites first (they strand dead producers),
#: then dead-op pruning, then feed pruning (a feed may only become
#: unused once the ops consuming it are gone).
DEFAULT_PIPELINE = (
    "collapse_redundant_casts",
    "cancel_redundant_transposes",
    "common_subexpression_elimination",
    "prune_dead_ops",
    "prune_unused_feeds",
)

_M_RUNS = _obs.counter(
    "opt.runs", "optimize_program invocations")
_M_FIXED = _obs.counter(
    "opt.findings_fixed",
    "lint findings eliminated by the rewrite pipeline, by PTL code")
_M_REMAINING = _obs.gauge(
    "opt.findings_remaining",
    "lint findings the final re-lint still reports after the pipeline "
    "reached its fixed point, by PTL code")
_M_REWRITE_SECONDS = _obs.histogram(
    "opt.rewrite_seconds",
    "wall time of one lint-fix pass application (lint + fix + re-lint), "
    "by pass name")
_M_ITERATIONS = _obs.gauge(
    "opt.fixedpoint_iterations",
    "pipeline repetitions until an iteration changed nothing, for the "
    "last optimize_program call")
_M_OPS_REMOVED = _obs.counter(
    "opt.ops_removed",
    "program instructions removed across all optimize_program calls")
_M_PASSES_SKIPPED = _obs.counter(
    "opt.passes_skipped",
    "lint-fix passes the benefit-ordered scheduler skipped because the "
    "pre-iteration lint sweep found nothing for their code, by pass name")


@dataclass
class OptimizeResult:
    """What one :func:`optimize_program` call did."""

    iterations: int = 0
    ops_before: int = 0
    ops_after: int = 0
    findings_fixed: Dict[str, int] = field(default_factory=dict)
    pruned_feeds: List[str] = field(default_factory=list)
    remaining: Optional[DiagnosticReport] = None
    #: pass name -> iterations the scheduler skipped it (pre-iteration
    #: lint sweep found nothing for its code)
    passes_skipped: Dict[str, int] = field(default_factory=dict)
    #: PTL303 no-benefit report: passes that never ran across the call
    no_benefit: Optional[DiagnosticReport] = None
    #: the order passes actually ran in, per iteration (benefit-ordered)
    schedule: List[List[str]] = field(default_factory=list)

    @property
    def ops_removed(self) -> int:
        return self.ops_before - self.ops_after

    @property
    def total_fixed(self) -> int:
        return sum(self.findings_fixed.values())

    @property
    def total_skipped(self) -> int:
        return sum(self.passes_skipped.values())

    def render(self) -> str:
        per_code = ", ".join(f"{c}={n}"
                             for c, n in sorted(self.findings_fixed.items()))
        return (f"optimize_program: {self.total_fixed} finding(s) fixed "
                f"({per_code or 'none'}), ops {self.ops_before} -> "
                f"{self.ops_after}, {self.iterations} iteration(s), "
                f"{self.total_skipped} pass-skip(s), "
                f"{len(self.remaining or [])} finding(s) remaining")


def _resolve_fetch(program, fetch) -> tuple:
    vids = []
    for t in fetch:
        vids.append(t if isinstance(t, int) else program.vid_of(t))
    return tuple(vids)


def _pass_code(name: str) -> str:
    """The PTL code a registered lint-fix pass claims ('' for passes
    outside the lint-fix family — those are never cost-gated)."""
    from ...distributed.passes import _PASS_REGISTRY

    return getattr(_PASS_REGISTRY.get(name), "code", "") or ""


def _benefit_weights(program, fetch_vids, sweep,
                     codes: Sequence[str], placements) -> Dict[str, float]:
    """code -> multiplicative benefit weight in [1, 2] from the static
    cost model: 1 + (the predicted per-op seconds of the code's
    findings) / (the program's total predicted seconds). A code whose
    findings sit on the expensive ops (a dead matmul, a redundant
    transpose of a sharded activation whose reshard the comm model
    prices) outweighs one whose findings are cheap casts — while the
    bounded range keeps finding COUNT the dominant term, so the
    established most-findings-first ordering only changes where counts
    tie. Priced WITHOUT liveness roots on purpose: a rewrite finding's
    worth is the static price of the op it removes or simplifies, and
    the dead ops PTL101 targets are exactly the ones a fetch-rooted
    sweep would zero out. Empty on any model failure: scheduling must
    never be the thing that breaks optimize_program."""
    from .cost import program_cost

    try:
        pc = program_cost(program, placements=placements)
    except Exception:
        return {}
    per_op = pc.seconds_by_op
    total = sum(per_op)
    if total <= 0:
        return {}
    weights: Dict[str, float] = {}
    for c in codes:
        secs = sum(per_op[d.op_index] for d in sweep.by_code(c)
                   if d.op_index is not None
                   and 0 <= d.op_index < len(per_op))
        weights[c] = 1.0 + min(secs / total, 1.0)
    return weights


def _iteration_schedule(names: Sequence[str], counts: Dict[str, int],
                        weights: Optional[Dict[str, float]] = None
                        ) -> tuple:
    """(runnable_in_benefit_order, skipped) for one iteration.

    Benefit = the lint sweep's finding count for the pass's code (every
    finding is one fixable rewrite), scaled by the cost-model weight
    from :func:`_benefit_weights` (expensive-op findings first when
    counts tie — comm-aware when a placement table is given); cost =
    the pass's observed mean wall time from ``opt.rewrite_seconds``
    (the measured-benefit data PR 11 started recording — a process that
    has run the pipeline before schedules from its own history, a fresh
    one falls back to a uniform prior and the order degrades to
    most-findings-first). Passes without a claimed code are never
    gated. Ties keep the static pipeline order (the sort is stable on
    the original index)."""
    weights = weights or {}
    runnable, skipped = [], []
    for i, n in enumerate(names):
        code = _pass_code(n)
        if code and counts.get(code, 0) <= 0:
            skipped.append(n)
            continue
        findings = counts.get(code, 1) if code else 1
        stats = _M_REWRITE_SECONDS.stats(name=n)
        observed = stats["avg"] if stats["count"] else 0.0
        score = findings * weights.get(code, 1.0) / max(observed, 1e-4)
        runnable.append((-score, i, n))
    runnable.sort()
    return [n for _s, _i, n in runnable], skipped


def optimize_program(program, fetch: Optional[Iterable] = None, *,
                     passes: Optional[Sequence[str]] = None,
                     max_iterations: int = 8,
                     verify: Optional[bool] = None,
                     schedule: bool = True,
                     placements=None) -> OptimizeResult:
    """Run the lint-fix pipeline over ``program`` until quiescence.

    ``fetch`` (Tensors or vids) names the values that must survive —
    the same liveness roots ``run_lints`` uses; without it (and without
    a recorded ``_fetch_vids``) the call refuses rather than guessing
    which outputs matter. Mutates ``program`` in place; the Executor
    hook optimizes a cached *clone* instead (static/program.py).

    ``schedule=True`` (default) cost-gates and benefit-orders each
    iteration from one shared lint sweep: zero-finding passes are
    skipped (``opt.passes_skipped``, PTL303 on the result), the rest
    run ordered by findings-per-observed-second, with each code's
    findings weighted by their predicted per-op seconds from the static
    cost model (``placements`` makes the weight COMM-aware: a finding
    sitting on an op whose placement forces a collective carries that
    collective's alpha-beta price too). ``schedule=False`` restores the
    static ``DEFAULT_PIPELINE`` order (every pass, every iteration).
    Both converge to the same fixed point — each pass is an independent
    re-lint-to-zero fix — so scheduling changes cost, never results
    (pinned by the bit-exact equivalence harness).

    ``verify=None`` inherits ``PADDLE_TPU_PASS_VERIFY`` via
    ``PassManager`` — every pass runs bracketed by the Program verifier
    in test/CI runs."""
    from ...distributed.passes import PassManager, new_pass

    if fetch is not None:
        fetch_vids = _resolve_fetch(program, fetch)
    else:
        fetch_vids = tuple(getattr(program, "_fetch_vids", ()) or ())
    if not fetch_vids:
        raise ValueError(
            "optimize_program needs fetch targets (pass fetch=... or "
            "record program._fetch_vids): liveness-based rewrites must "
            "know which values survive")

    on = _obs.state.on
    if on:
        _M_RUNS.inc()
    result = OptimizeResult(ops_before=program.num_ops)
    names = list(passes or DEFAULT_PIPELINE)
    sweep_codes = sorted({_pass_code(n) for n in names if _pass_code(n)})
    ran: set = set()
    t0 = time.perf_counter()
    feed_names_before = set(program._feed_names)

    while result.iterations < max_iterations:
        result.iterations += 1
        if schedule and sweep_codes:
            sweep = run_lints(program, fetch=fetch_vids,
                              codes=sweep_codes)
            counts = {c: len(sweep.by_code(c)) for c in sweep_codes}
            weights = _benefit_weights(program, fetch_vids, sweep,
                                       sweep_codes, placements)
            to_run, skipped = _iteration_schedule(names, counts, weights)
            if not to_run:
                break  # quiescent: nothing any pass could fix
            for n in skipped:
                result.passes_skipped[n] = \
                    result.passes_skipped.get(n, 0) + 1
                if on:
                    _M_PASSES_SKIPPED.inc(name=n)
        else:
            to_run = names
        fp_before = program.fingerprint()
        pm = PassManager(
            [new_pass(n, {"fetch": list(fetch_vids)}) for n in to_run],
            verify=verify)
        pm.apply(program, None)
        ran.update(to_run)
        result.schedule.append(list(to_run))
        for code, n in (pm.context.get_attr("findings_fixed")
                        or {}).items():
            result.findings_fixed[code] = \
                result.findings_fixed.get(code, 0) + n
        if program.fingerprint() == fp_before:
            break

    result.no_benefit = DiagnosticReport()
    for n in names:
        if n not in ran:
            result.no_benefit.add(
                "PTL303", Severity.NOTE,
                f"pass {n!r} never ran: the lint sweep found no "
                f"{_pass_code(n) or 'matching'} finding in any "
                f"iteration — zero predicted benefit, zero wall time "
                f"spent",
                hint="expected on already-clean programs; if the pass "
                     "should have fired, check the lint it pairs with")
    result.ops_after = program.num_ops
    result.pruned_feeds = sorted(
        feed_names_before - set(program._feed_names))
    result.remaining = run_lints(program, fetch=fetch_vids,
                                 codes=REWRITE_CODES)
    if on:
        _M_ITERATIONS.set(result.iterations)
        if result.ops_removed > 0:
            _M_OPS_REMOVED.inc(result.ops_removed)
        for code in REWRITE_CODES:
            _M_REMAINING.set(len(result.remaining.by_code(code)),
                             code=code)
        _obs.emit("opt.program_optimized",
                  seconds=time.perf_counter() - t0,
                  iterations=result.iterations,
                  findings_fixed=result.total_fixed,
                  ops_before=result.ops_before,
                  ops_after=result.ops_after,
                  remaining=len(result.remaining),
                  passes_skipped=result.total_skipped)
    return result
