"""Diagnostic records for the Program analysis layer.

Reference: the PIR verifier surfaces IrNotMetException with an op trace
(pir/core/ir_context + pir::Verify), and the inference analysis pipeline
logs per-pass findings (paddle/fluid/inference/analysis/analysis_pass.h).
Here both funnel into one coded record type so verifier errors and lint
warnings share formatting, filtering, and test assertions.

Code namespace (``PTLxxx``):

- ``PTL0xx`` — structural verifier errors (`verify.py`): the program is
  malformed and replay is undefined behaviour.
- ``PTL1xx`` — lint findings (`lint.py`): the program is valid but
  suspicious (dead code, redundant ops, silent dtype demotion, ...).
- ``PTL2xx`` — sharding-aware lints (`lint.py`/`sharding_lint.py`):
  layout/placement findings feeding the auto-parallel planner.
- ``PTL3xx`` — cost/memory analysis (`cost.py`/`memory.py`/
  `rewrite.py`): predicted OOM, cost-model drift, no-benefit passes.
- ``PTL4xx`` — serving observability (`observability/slo.py`,
  `observability/tracing.py`, `serve_trace_lint.py`): SLO breaches,
  tracing overhead, malformed span trees, decode-burst gaps,
  preemption thrash.
- ``PTL5xx`` — execution profiling (`observability/opprof.py`): per-op
  measured-vs-predicted drift, attribution shortfall, profiling
  overhead — the measured half of the PTL3xx cost model.
- ``PTL6xx`` — continuous health monitoring (`observability/health.py`,
  `tools/bench_compare.py`): time-series anomaly detectors (perf drift,
  resource leaks, throughput degradation) and BENCH regression gating.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = [
    "Severity", "Diagnostic", "DiagnosticReport",
    "ProgramVerificationError", "CODES",
]


class Severity(enum.IntEnum):
    NOTE = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):  # "error" not "Severity.ERROR" in rendered reports
        return self.name.lower()


# Registry of every code this layer can emit — one place to look up what a
# code means, and the source of truth tests assert against.
CODES = {
    # verifier (errors)
    "PTL001": "unknown primitive (not in dispatch.PRIMITIVES)",
    "PTL002": "use of an undefined value id (use-before-def or dangling input)",
    "PTL003": "duplicate value-id definition (out_vid redefined)",
    "PTL004": "dangling out_vid (value id was never allocated by this program)",
    "PTL005": "feed placeholder vid also bound as a constant",
    "PTL006": "unhashable static attribute (breaks executable caching)",
    "PTL007": "malformed __gradients__ instruction (placement/operands/fwd_len)",
    "PTL008": "InferMeta audit: recorded output shape diverges from eval_shape",
    "PTL009": "InferMeta audit: recorded output dtype diverges from eval_shape",
    "PTL010": "InferMeta audit: shape inference failed or output arity mismatch",
    # lints (warnings/notes)
    "PTL101": "dead op: outputs never reach a fetch target",
    "PTL102": "unused feed: placeholder is never consumed",
    "PTL103": "redundant cast (no-op cast or losslessly collapsible chain)",
    "PTL104": "redundant transpose chain (cancels out or composes to one)",
    "PTL105": "common-subexpression candidate (identical op computed twice)",
    "PTL106": "silent float64 -> float32 demotion",
    "PTL107": "non-jittable primitive inside a jit-replayed program",
    "PTL108": "cast chain with a narrowing intermediate (numerics-changing, "
              "NOT redundant — informational only)",
    # sharding-aware lints (PTL2xx) — layout/placement findings feeding
    # the auto-parallel planner (lint.py + sharding_lint.py)
    "PTL201": "float32 operand on a bfloat16 compute hot path (mixed-dtype "
              "GEMM upcasts to the fp32 rate)",
    "PTL202": "placement mismatch forces an avoidable collective (reshard/"
              "allgather a consistent plan would not need)",
    "PTL203": "collective serializes against compute in the merged fleet "
              "trace (no overlap with any compute span on that rank)",
    # cost/memory-analysis diagnostics (PTL3xx) — the static cost model
    # and liveness peak-memory estimator (cost.py + memory.py)
    "PTL301": "predicted OOM before compile: liveness peak-memory estimate "
              "exceeds the device budget",
    "PTL302": "cost-model drift: analytical FLOPs estimate diverges from "
              "XLA's compiled cost analysis beyond tolerance",
    "PTL303": "no-benefit pass: a rewrite pass was scheduled out because "
              "the pre-pass lint found nothing it could fix",
    "PTL304": "step-time model drift: predicted step time (compute + "
              "comm model) diverges from measured train.step_seconds "
              "beyond tolerance",
    "PTL305": "auto-sharding search found a placement predicted strictly "
              "faster than the derived plan (informational: the derived "
              "plan is not comm-optimal)",
    # serving-observability diagnostics (PTL4xx) — request-lifecycle
    # tracing + SLO guardrails (observability/slo.py + tracing.py +
    # serve_trace_lint.py)
    "PTL401": "SLO breach: a declarative rolling-window serving rule "
              "(p99 TTFT / tokens-per-sec floor / pool-exhaustion rate) "
              "left its bound",
    "PTL402": "tracing overhead exceeded: tokens/sec with request "
              "tracing enabled fell more than the tolerance below the "
              "untraced run",
    "PTL403": "span-tree malformed: a request's lifecycle spans are "
              "unclosed, out of order, or escape the request envelope",
    "PTL404": "decode-burst gap: the engine sat host-side between decode "
              "steps while slots were runnable (fused multi-token decode "
              "would close the gap)",
    "PTL405": "preemption thrash: the same request was preempted and "
              "recomputed too many times (pool sizing / admission "
              "pressure)",
    # execution-profiling diagnostics (PTL5xx) — the op-level profiler
    # that closes the predicted-vs-measured loop (observability/opprof.py)
    "PTL501": "hot-op drift: a profiled op's measured time diverges from "
              "the cost model's per-op prediction beyond tolerance (the "
              "per-op decomposition of PTL302/PTL304)",
    "PTL502": "attribution shortfall: the op profiler's spans fail to "
              "tile the measured step (unattributed step time above "
              "threshold — the profile cannot be trusted)",
    "PTL503": "profiling overhead exceeded: steps/sec with op profiling "
              "enabled fell more than the budget below the unprofiled "
              "run (the PTL402 analog for the training plane)",
    # continuous-health diagnostics (PTL6xx) — detectors evaluated over
    # metric time-series (observability/health.py) plus the BENCH
    # record comparator (tools/bench_compare.py)
    "PTL601": "perf drift: a step-time series drifted beyond the "
              "z-score/relative-change gate against its own baseline "
              "window (the continuous form of PTL302 — no model needed, "
              "the job is compared against its younger self)",
    "PTL602": "resource leak: a watermark/occupancy series grows "
              "monotonically across the observation window (HBM "
              "watermark, KV-pool occupancy, host-side ring sizes) — "
              "the job will eventually OOM or thrash",
    "PTL603": "throughput degradation: a rate series (tokens/sec, or a "
              "failure counter's rate-of-change) left its healthy band "
              "— serving slowdown or elastic/fleet instability",
    "PTL604": "detector input malformed: a health rule's series is "
              "missing, non-numeric, or non-finite — the detector "
              "cannot evaluate and says so instead of staying silent",
    "PTL605": "regression vs baseline: a benchmark config's headline "
              "metric moved beyond the noise band against the previous "
              "BENCH record (tools/bench_compare.py CI gate)",
}


@dataclass
class Diagnostic:
    """One finding: coded, located, and actionable.

    ``op_index`` is the instruction index in ``Program._insts`` (None for
    program-level findings like feed/const overlap). ``suggestion`` is an
    optional machine-readable fix payload — a plain JSON-able dict so
    automated consumers (the PADDLE_TPU_REPLACEMENT re-placement hook in
    auto_parallel/completion.py reads PTL202 payloads) act on structure
    instead of parsing the rendered message."""

    code: str
    severity: Severity
    message: str
    op_index: Optional[int] = None
    hint: Optional[str] = None
    suggestion: Optional[dict] = None

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unregistered diagnostic code {self.code!r}")

    def render(self) -> str:
        loc = f"op#{self.op_index}: " if self.op_index is not None else ""
        s = f"{self.code} {self.severity}: {loc}{self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def __str__(self):
        return self.render()


@dataclass
class DiagnosticReport:
    """Ordered collection of diagnostics with an overall verdict."""

    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(self, code, severity, message, op_index=None, hint=None,
            suggestion=None):
        self.diagnostics.append(
            Diagnostic(code, severity, message, op_index, hint, suggestion))

    def extend(self, other: "DiagnosticReport"):
        self.diagnostics.extend(other.diagnostics)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self):
        return {d.code for d in self.diagnostics}

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def render(self, header: Optional[str] = None) -> str:
        lines = []
        if header:
            lines.append(header)
        if not self.diagnostics:
            lines.append("no diagnostics")
        lines.extend(d.render() for d in self.diagnostics)
        return "\n".join(lines)

    def raise_if_errors(self, context: Optional[str] = None):
        if self.errors:
            raise ProgramVerificationError(self, context=context)

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __str__(self):
        return self.render()


class ProgramVerificationError(RuntimeError):
    """Raised when verification finds structural errors.

    ``context`` carries provenance — the PassManager attaches the name of
    the rewrite pass after which verification failed (the pir::PassManager
    verify-between-passes behaviour)."""

    def __init__(self, report: DiagnosticReport, context: Optional[str] = None):
        self.report = report
        self.context = context
        where = f" [{context}]" if context else ""
        errs = report.errors
        msg = (f"program verification failed{where}: "
               f"{len(errs)} error(s)\n" +
               "\n".join(d.render() for d in errs))
        super().__init__(msg)
