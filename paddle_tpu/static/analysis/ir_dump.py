"""Textual IR dump of a captured static ``Program``.

Reference: pir::Program::Print / the `print_ir` hooks pass managers use
around every pass (pir/include/pass/pass_manager.h EnableIRPrinting) —
diagnostics are only actionable when the IR they point into is readable.
The dump names every vid, shows feed/const provenance, static attrs, and
best-effort result avals from the InferMeta propagation, so a
``PTL008 op#3`` report can be read directly against ``Program.dump()``.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from .verify import GRAD_OP, propagate_avals

__all__ = ["dump_program"]

_MAX_ATTR_CHARS = 60


def _fmt_aval(aval) -> str:
    if aval is None:
        return "?"
    shape, dtype = aval
    return f"{np.dtype(dtype).name}[{'x'.join(map(str, shape))}]"


def _fmt_attr_value(v) -> str:
    s = repr(v)
    return s if len(s) <= _MAX_ATTR_CHARS else s[:_MAX_ATTR_CHARS - 3] + "..."


def _fmt_attrs(static_items) -> str:
    try:
        items = [(k, v) for k, v in static_items]
    except (TypeError, ValueError):
        # malformed attrs (the verifier reports them; the dump must
        # still render so the report is readable against it)
        return f" {{{static_items!r}}}"
    if not items:
        return ""
    body = ", ".join(f"{k}={_fmt_attr_value(v)}" for k, v in items)
    return f" {{{body}}}"


def dump_program(program, *, annotate: bool = True) -> str:
    """Render the instruction list as readable IR text.

    ``annotate=False`` skips the eval_shape-based aval propagation (cheap
    dump for very large programs); vids then print without types."""
    avals: Dict[int, tuple] = propagate_avals(program) if annotate else {}

    def ty(vid) -> str:
        # ": <aval>" suffix, empty when annotation is off
        return f" : {_fmt_aval(avals.get(vid))}" if annotate else ""

    n_grad = sum(1 for i in program._insts if i[0] == GRAD_OP)
    head = (f"Program({len(program._insts)} ops, "
            f"{len(program._placeholders)} feeds, "
            f"{len(program._consts)} consts"
            + (f", {n_grad} grad section(s)" if n_grad else "") + ")")
    lines = [head]

    for name, vid, shape, dtype in program._placeholders:
        declared = tuple(shape)
        lines.append(f"  %{vid} = feed \"{name}\"{ty(vid)}"
                     f"  # declared {declared}, dtype={dtype}")
    for vid in sorted(program._consts):
        lines.append(f"  %{vid} = const{ty(vid)}")

    for idx, inst in enumerate(program._insts):
        try:
            prim_name, in_vids, static_items, out_vids = inst
        except (TypeError, ValueError):
            lines.append(f"  op#{idx}: <malformed instruction {inst!r}>")
            continue
        outs = ", ".join(f"%{v}" for v in out_vids) or "()"
        if prim_name == GRAD_OP:
            loss = f"%{in_vids[0]}" if in_vids else "?"
            wrt = ", ".join(f"%{v}" for v in in_vids[1:])
            lines.append(
                f"  op#{idx}: {outs} = __gradients__(loss={loss}; "
                f"wrt=[{wrt}]){_fmt_attrs(static_items)}")
            continue
        ins = ", ".join(f"%{v}" for v in in_vids)
        if annotate:
            restype = " : " + (", ".join(_fmt_aval(avals.get(v))
                                         for v in out_vids) or "()")
        else:
            restype = ""
        lines.append(f"  op#{idx}: {outs} = {prim_name}({ins})"
                     f"{_fmt_attrs(static_items)}{restype}")

    if getattr(program, "_fetch_vids", None):
        lines.append("  fetch: " + ", ".join(
            f"%{v}" for v in program._fetch_vids))
    if getattr(program, "_remat_checkpoints", None):
        lines.append("  remat checkpoints: " + ", ".join(
            f"%{v}" for v in program._remat_checkpoints))
    return "\n".join(lines)
