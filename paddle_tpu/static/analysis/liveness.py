"""Shared liveness analysis over the flat instruction list.

ONE implementation of backward reachability, used by BOTH the PTL101
dead-op lint (lint.py) and the rewrite passes that delete code
(``dead_code_elimination`` / ``prune_dead_ops`` in
distributed/passes/) — so the lint and the pass can never disagree
about what is dead. Before this module each side reimplemented the
sweep and a divergence (e.g. one treating effectful ops as roots and
the other not) would have made the lint report ops the pass refuses to
delete, or worse, the pass delete ops the lint considers live.

Liveness roots, in both directions of the loop:

- any op producing a value in ``live_vids`` (the fetch targets);
- effectful ops (RNG/state/IO — their *execution* is the point, the
  outputs reaching a fetch is not required);
- the ``__gradients__`` pseudo-op (its replay drives the backward; its
  operands must stay live even when only the grads are fetched).
"""
from __future__ import annotations

from typing import Iterable, Sequence, Set

from .verify import GRAD_OP

__all__ = ["EFFECTFUL_MARKERS", "is_effectful", "live_op_indices"]

#: prims whose value depends on RNG/state or that perform IO: never
#: DCE/CSE candidates — substrings matched case-insensitively.
EFFECTFUL_MARKERS = ("rand", "uniform", "normal", "dropout", "bernoulli",
                     "poisson", "multinomial", "exponential", "seed",
                     "print", "py_func", "barrier")


def is_effectful(prim_name: str) -> bool:
    low = prim_name.lower()
    return any(m in low for m in EFFECTFUL_MARKERS)


def live_op_indices(insts: Sequence[tuple],
                    live_vids: Iterable[int], *,
                    pin_grads: bool = True) -> Set[int]:
    """Indices of instructions that are live w.r.t. ``live_vids``.

    Single backward sweep: an op is kept when any of its outputs is
    live (feeds a later live op or a fetch target), when it is
    effectful, or when it is the ``__gradients__`` section; kept ops
    propagate liveness to their inputs.

    ``pin_grads=True`` (the rewrite/lint view) keeps ``__gradients__``
    unconditionally — deleting it is never safe for a rewrite because
    a later caller may fetch the grads. ``pin_grads=False`` (the
    cost/memory view, ``cost.executed_op_indices``) keeps it only when
    its outputs are live — what XLA actually executes, since an
    unfetched grad section is DCE'd out of the compiled replay. ONE
    sweep serves both so the two views can never diverge on anything
    but that single, named difference."""
    live: Set[int] = set(live_vids)
    kept: Set[int] = set()
    for idx in range(len(insts) - 1, -1, -1):
        prim_name, in_vids, _static, out_vids = insts[idx]
        if prim_name == GRAD_OP:
            if not pin_grads and not any(v in live for v in out_vids):
                continue
        elif not any(v in live for v in out_vids) \
                and not is_effectful(prim_name):
            continue
        kept.add(idx)
        live.update(in_vids)
    return kept
