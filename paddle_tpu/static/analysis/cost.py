"""Analytical per-op cost model over the captured static ``Program``.

Reference: the reference stacks a cost model on its IR for pass
scheduling and placement (auto_parallel/static/cost/ — ``CommOpCost``/
``CompOpCost`` per op, ``CostEstimator`` walking the program); here the
same split of labor lands on the flat instruction list: a per-prim
FLOPs/bytes registry (:func:`op_cost`, keyed by operand/result avals
from ``verify.propagate_avals``) and a program walker
(:func:`program_cost`) that restricts to the ops live w.r.t. the fetch
set — dead ops cost nothing because XLA DCEs them before they execute.

Two ground truths keep the model honest, both already measured by the
repo:

- FLOPs: ``observability.runtime.measure_step_flops`` (XLA's compiled
  cost analysis — the post-fusion count the hardware executes).
  :func:`check_cost_model` compares and files **PTL302** when the
  analytical estimate drifts beyond tolerance — the cost-model-rot
  alarm.
- Peak HBM: the PR 5 ``device.hbm_watermark_bytes`` gauge, against the
  liveness-interval estimator in ``memory.py`` (which files PTL301).

The ``__gradients__`` pseudo-op is modeled as ``3x`` the FLOPs of the
forward sub-replay live w.r.t. the loss: the Executor replays the
gradient section as ``jax.grad`` of a fresh forward trace (one more
forward) plus the backward (~2x forward — each matmul's VJP is two
matmuls of equal cost). Measured on the bench llama train program the
whole-program count then lands within a few percent of XLA's
(fwd + 3x fwd = 4.0x; XLA reports 4.03x).

Everything here is static — no compile, no device. The one consumer
that pays a compile is :func:`measure_program_flops`, the validation
helper that runs XLA's cost analysis on a compiled replay of the same
program so predicted and measured count the SAME executable.

Metrics ride the claimed ``cost.`` subsystem
(``observability.metrics.CLAIMED_SUBSYSTEMS``): predicted/measured
FLOPs and peak-HBM gauges (by program ``name``), the model-error
gauge PTL302 reads, and the estimate wall-time histogram.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ... import observability as _obs
from .diagnostics import DiagnosticReport, Severity
from .liveness import live_op_indices
from .verify import GRAD_OP, propagate_avals

__all__ = [
    "OpCost", "ProgramCost", "op_cost", "register_op_cost",
    "program_cost", "measure_program_flops", "check_cost_model",
    "check_step_time_model", "executed_op_indices",
    "COST_ANALYSIS_CODES",
]

#: the diagnostic codes the cost/memory analysis layer can file —
#: audited by tools/lint_registry.py the same way lint.LINTS and the
#: sharding-lint codes are (documented in diagnostics.CODES, exercised
#: by at least one test). PTL304/305 belong to the step-time model +
#: auto-sharding search (comm_cost.py + auto_parallel/completion.py).
COST_ANALYSIS_CODES = ("PTL301", "PTL302", "PTL303", "PTL304", "PTL305")

M_PREDICTED_FLOPS = _obs.gauge(
    "cost.predicted_flops",
    "analytical per-op cost-model FLOPs of a program replay, by program "
    "name")
M_MEASURED_FLOPS = _obs.gauge(
    "cost.measured_flops",
    "XLA compiled-cost-analysis FLOPs of the same program replay, by "
    "program name (the ground truth cost.predicted_flops is validated "
    "against)")
M_FLOPS_ERROR = _obs.gauge(
    "cost.model_flops_error_pct",
    "percent error of the analytical FLOPs model vs XLA's compiled "
    "cost analysis, by program name (PTL302 fires when it exceeds "
    "tolerance)")
M_PREDICTED_PEAK = _obs.gauge(
    "cost.predicted_peak_hbm_bytes",
    "liveness-interval peak-memory estimate of a program replay, by "
    "program name (memory.estimate_peak_memory)")
M_MEASURED_PEAK = _obs.gauge(
    "cost.measured_peak_hbm_bytes",
    "device.hbm_watermark_bytes observed when the predicted-vs-measured "
    "comparison ran, by program name (copied next to the prediction so "
    "one dump renders the whole table)")
M_PREDICTED_STEP = _obs.gauge(
    "cost.predicted_step_seconds",
    "predicted step time max(compute, memory) + comm of a program "
    "replay under its placement table, by program name (the number the "
    "auto-sharding search ranks plans by)")
M_MEASURED_STEP = _obs.gauge(
    "cost.measured_step_seconds",
    "mean measured train.step_seconds observed when the predicted-vs-"
    "measured step-time comparison ran, by program name (copied next "
    "to the prediction so one dump renders the whole table)")
M_STEP_ERROR = _obs.gauge(
    "cost.model_step_error_pct",
    "percent error of the predicted step time vs measured "
    "train.step_seconds, by program name (PTL304 fires when it exceeds "
    "tolerance)")
M_ESTIMATE_SECONDS = _obs.histogram(
    "cost.estimate_seconds",
    "wall time of one static cost/memory estimate, by analysis kind")
M_PREDICTED_OOM = _obs.counter(
    "cost.predicted_oom",
    "PTL301 firings: programs whose peak-memory estimate exceeded the "
    "device budget before compile, by program name")


@dataclass(frozen=True)
class OpCost:
    """Cost of one instruction: arithmetic + memory traffic + footprint."""

    flops: int = 0
    bytes_read: int = 0
    bytes_written: int = 0

    @property
    def bytes_total(self) -> int:
        return self.bytes_read + self.bytes_written


@dataclass
class ProgramCost:
    """Aggregate of one program replay (live ops only)."""

    flops: int = 0
    bytes_read: int = 0
    bytes_written: int = 0
    by_op: List[OpCost] = field(default_factory=list)
    flops_by_prim: Dict[str, int] = field(default_factory=dict)
    live_ops: int = 0
    unknown_avals: int = 0
    #: step-time decomposition (comm_cost.CommModelParams machine
    #: model): per-chip FLOPs / achieved rate, per-chip HBM traffic /
    #: bandwidth, the alpha-beta comm model over the placement table,
    #: and the roofline-style composite the auto-sharding search ranks
    #: plans by. comm holds the full CommCostResult (None when no
    #: placements were given — a single-chip replay has no comm).
    compute_seconds: float = 0.0
    memory_seconds: float = 0.0
    comm_seconds: float = 0.0
    predicted_step_seconds: float = 0.0
    seconds_by_op: List[float] = field(default_factory=list)
    comm: Optional[object] = None  # comm_cost.CommCostResult

    def render(self) -> str:
        top = sorted(self.flops_by_prim.items(), key=lambda kv: -kv[1])[:8]
        per = ", ".join(f"{k}={v:,}" for k, v in top)
        return (f"program cost: {self.flops:,} flops over {self.live_ops} "
                f"live op(s), {self.bytes_read:,}B read / "
                f"{self.bytes_written:,}B written, predicted step "
                f"{self.predicted_step_seconds * 1e3:.3f}ms "
                f"(compute {self.compute_seconds * 1e3:.3f} / memory "
                f"{self.memory_seconds * 1e3:.3f} / comm "
                f"{self.comm_seconds * 1e3:.3f}) ({per})")


Aval = Tuple[Tuple[int, ...], np.dtype]


def _numel(aval: Optional[Aval]) -> int:
    if aval is None:
        return 0
    return int(np.prod(aval[0])) if aval[0] else 1


def _nbytes(aval: Optional[Aval]) -> int:
    if aval is None:
        return 0
    return _numel(aval) * np.dtype(aval[1]).itemsize


# ---------------------------------------------------------------------------
# per-prim FLOPs registry
# ---------------------------------------------------------------------------

# fn(in_avals, out_avals, attrs) -> flops. Registered exactly, then
# matched by family marker, then the elementwise default (one flop per
# output element) — the same fallback ladder utils/flops.py uses for
# the eager per-op registry.
_FLOPS_FNS: Dict[str, Callable] = {}


def register_op_cost(*prim_names: str):
    """Register an exact-FLOPs function for one or more prims."""

    def deco(fn):
        for name in prim_names:
            _FLOPS_FNS[name] = fn
        return fn

    return deco


def _contracting_dim(shape: Tuple[int, ...], transposed: bool) -> int:
    if len(shape) >= 2 and transposed:
        return shape[-2]
    return shape[-1] if shape else 1


@register_op_cost("matmul", "matmul_p", "matmul_v2", "bmm",
                  "linear_nobias_p")
def _matmul_flops(in_avals, out_avals, attrs):
    # 2 * (output elements) * K: exact for any batched/broadcast matmul
    x = in_avals[0]
    if x is None or out_avals[0] is None:
        return 0
    k = _contracting_dim(x[0], bool(attrs.get("transpose_x")
                                    or attrs.get("trans_x")))
    return 2 * _numel(out_avals[0]) * k


@register_op_cost("linear_p")
def _linear_flops(in_avals, out_avals, attrs):
    # x @ W + b: the bias add is one flop per output element
    return _matmul_flops(in_avals, out_avals, attrs) \
        + _numel(out_avals[0])


@register_op_cost("fused_linear_ce_p")
def _fused_linear_ce_flops(in_avals, out_avals, attrs):
    # hidden @ vocab-head GEMM + softmax-CE over the logits it never
    # materializes: 2*rows*H*V for the GEMM, ~5 flops per logit for CE
    x, w = (in_avals + [None, None])[:2]
    if x is None or w is None:
        return 0
    rows = _numel(x) // max(x[0][-1], 1)
    h = x[0][-1]
    v = w[0][-1] if len(w[0]) >= 2 else 1
    return 2 * rows * h * v + 5 * rows * v


@register_op_cost("conv_p", "conv_transpose_p")
def _conv_flops(in_avals, out_avals, attrs):
    # 2 * out_elements * (C_in/groups) * prod(kernel): implicit GEMM
    x, w = (in_avals + [None, None])[:2]
    if x is None or w is None or out_avals[0] is None:
        return 0
    wshape = w[0]
    if len(wshape) < 3:
        return 2 * _numel(out_avals[0]) * _numel(w)
    cin_g = wshape[1]
    kernel = int(np.prod(wshape[2:]))
    return 2 * _numel(out_avals[0]) * cin_g * kernel


@register_op_cost("sdpa_p", "sdpa_mask_p")
def _sdpa_flops(in_avals, out_avals, attrs):
    # q [B,S,H,D] (capture layout): scores + context are 2x 2*B*H*S*Skv*D,
    # softmax ~5 flops per score element
    q, k = (in_avals + [None, None])[:2]
    if q is None or k is None:
        return 0
    d = q[0][-1] if q[0] else 1
    s_kv = k[0][1] if len(k[0]) >= 2 else 1
    nq = _numel(q)
    return 4 * nq * s_kv + 5 * (nq // max(d, 1)) * s_kv


@register_op_cost("rms_norm_p", "layer_norm_p", "group_norm_p",
                  "instance_norm_p", "batch_norm_train_p",
                  "batch_norm_infer_p")
def _norm_flops(in_avals, out_avals, attrs):
    return 4 * _numel(in_avals[0] if in_avals else None)


@register_op_cost("softmax_p", "log_softmax_p", "hard_ce_p", "soft_ce_p",
                  "swiglu_p")
def _softmaxish_flops(in_avals, out_avals, attrs):
    # ~5 flops per input element (max/sub/exp/sum/div; swiglu is
    # sigmoid+2 muls) — matches XLA's count within a few percent
    return 5 * _numel(in_avals[0] if in_avals else None)


@register_op_cost("fused_rope_p")
def _rope_flops(in_avals, out_avals, attrs):
    # rotate-half: 2 muls + 1 add per element, on q and k (first two
    # operands); XLA counts 3.5/element with the sign flip folded in
    n = sum(_numel(a) for a in in_avals[:2])
    return (7 * n) // 2


@register_op_cost("moe_idx_ffn_p")
def _moe_flops(in_avals, out_avals, attrs):
    # routed 2-GEMM FFN on the gathered tokens: 2 * tokens*topk * 2*H*I
    x = in_avals[0] if in_avals else None
    banks = [a for a in in_avals[1:] if a is not None and len(a[0]) >= 3]
    if x is None or not banks:
        return 0
    h = x[0][-1]
    rows = _numel(x) // max(h, 1)
    inter = banks[0][0][-1]
    top_k = int(attrs.get("top_k", attrs.get("k", 1)) or 1)
    return 2 * rows * top_k * (2 * h * inter)


@register_op_cost("embedding_p", "gather_p", "gather_nd_p",
                  "take_along_axis_p", "one_hot_p")
def _gather_flops(in_avals, out_avals, attrs):
    return 0  # pure data movement; bytes carry the cost


#: prims that move/re-view data without arithmetic — zero FLOPs, the
#: bytes columns carry their cost.
_MOVEMENT_PRIMS = frozenset({
    "reshape_p", "transpose_p", "flatten_p", "squeeze_p", "unsqueeze_p",
    "slice_p", "getitem_p", "setitem_p", "split_p", "stack_p", "tile_p",
    "broadcast_to_p", "pad_p", "where_p", "tril", "triu",
})
_MOVEMENT_PREFIXES = ("concat_",)


def op_cost(prim_name: str, in_avals: Iterable[Optional[Aval]],
            out_avals: Iterable[Optional[Aval]],
            attrs: Optional[dict] = None) -> OpCost:
    """Analytical cost of one instruction from its operand/result avals.

    FLOPs resolve through the registry, then the movement set (0), then
    the elementwise default (one flop per output element — right for
    add/mul/compare, and a rounding error for anything the registry
    does not know, since unknown prims are by construction not the
    compute-dominant ones). Bytes are exact: operand reads + result
    writes at aval itemsize."""
    in_avals = list(in_avals)
    out_avals = list(out_avals)
    attrs = attrs or {}
    fn = _FLOPS_FNS.get(prim_name)
    if fn is not None:
        flops = int(fn(in_avals, out_avals, attrs))
    elif prim_name in _MOVEMENT_PRIMS \
            or prim_name.startswith(_MOVEMENT_PREFIXES):
        flops = 0
    elif prim_name.startswith("reduce_"):
        flops = _numel(in_avals[0] if in_avals else None)
    else:
        flops = sum(_numel(a) for a in out_avals)
    return OpCost(flops=flops,
                  bytes_read=sum(_nbytes(a) for a in in_avals),
                  bytes_written=sum(_nbytes(a) for a in out_avals))


# backward FLOPs multiplier for the __gradients__ sub-replay: one more
# forward (jax.grad re-traces the loss) + ~2x forward for the backward
_GRAD_FLOPS_MULTIPLIER = 3


def executed_op_indices(insts, fetch_vids) -> set:
    """Ops XLA actually EXECUTES for this fetch set: the shared
    liveness sweep WITHOUT the unconditional ``__gradients__`` pin —
    a rewrite must keep an unfetched grad section (a later caller may
    fetch the grads), but XLA DCEs it out of the compiled executable,
    so cost and memory estimates must not charge for it."""
    return live_op_indices(insts, fetch_vids, pin_grads=False)


def _resolve_fetch_vids(program, fetch) -> Tuple[int, ...]:
    if fetch is not None:
        return tuple(t if isinstance(t, int) else program.vid_of(t)
                     for t in fetch)
    return tuple(getattr(program, "_fetch_vids", ()) or ())


def _shard_divisor(spec) -> int:
    """How many ways a value's BYTES split across the mesh under
    ``spec`` (product of mesh-axis sizes carrying a Shard) — per-chip
    footprints divide by this. Partial axes deliberately do NOT count:
    a pending-reduce value occupies its full shape on every chip."""
    if spec is None:
        return 1
    div = 1
    for axis, p in enumerate(spec.placements):
        if p.is_shard():
            div *= int(spec.mesh.shape[axis])
    return max(div, 1)


def _compute_divisor(spec) -> int:
    """How many ways the COMPUTE producing a value splits: Shard axes
    (each chip produces a slice) times Partial axes (each chip did
    1/n of the contraction — the row-parallel matmul the PTL202 lint
    recommends has a Partial output but 8x-split FLOPs)."""
    if spec is None:
        return 1
    div = 1
    for axis, p in enumerate(spec.placements):
        if p.is_shard() or p.is_partial():
            div *= int(spec.mesh.shape[axis])
    return max(div, 1)


#: matmul-family prims whose contraction can split across a mesh axis.
_CONTRACTION_PRIMS = frozenset(
    ("matmul", "linear_nobias_p", "linear_p", "bmm",
     "matmul_p", "bmm_p"))


def _contraction_divisor(prim_name, attrs, in_specs, out_specs) -> int:
    """Extra per-chip compute credit for contraction splits whose
    COMPLETED output replicates: both matmul operands shard their
    contracting dims on a mesh axis, so each chip does 1/n of the
    multiply-adds — but once the psum materializes the output as
    Replicate (the ``contract8`` bench geometry), ``_compute_divisor``
    sees nothing to divide and the plan reads n-times pessimistic.
    Mesh axes the output DOES count (Shard or Partial there) are
    skipped: those already divide via ``_compute_divisor``, and
    crediting them twice would halve row-parallel plans again."""
    if prim_name not in _CONTRACTION_PRIMS or len(in_specs) < 2:
        return 1
    x, w = in_specs[0], in_specs[1]
    if x is None or w is None:
        return 1
    from .sharding_lint import matmul_contracting_dims

    x_c, w_c = matmul_contracting_dims(attrs, x.ndim, w.ndim)
    div = 1
    for axis, px in enumerate(x.placements):
        pw = w.placements[axis] if axis < len(w.placements) else None
        if pw is None or not (px.is_shard(x_c) and pw.is_shard(w_c)):
            continue
        if any(o is not None and axis < len(o.placements)
               and (o.placements[axis].is_shard()
                    or o.placements[axis].is_partial())
               for o in out_specs):
            continue
        div *= int(x.mesh.shape[axis])
    return max(div, 1)


def program_cost(program, fetch=None, *, placements=None, mesh=None,
                 avals: Optional[Dict[int, Aval]] = None,
                 params=None, op_calibration=None) -> ProgramCost:
    """Walk the program once and sum per-op costs over the LIVE ops.

    ``fetch`` (Tensors or vids; falls back to a recorded
    ``_fetch_vids``) roots the liveness sweep — without any roots every
    op counts, the conservative read. ``placements`` (vid ->
    DistTensorSpec) makes the estimate per-chip: each value's bytes
    divide by its shard count (Partial values occupy full shape on
    every chip), and each op's FLOPs divide by its output's COMPUTE
    split — Shard axes plus Partial axes, so a row-parallel matmul
    whose output is Partial still counts as contraction-split.
    ``mesh`` alone (a ProcessMesh, no placements) derives the table
    via ``auto_parallel.completion.complete_placements`` first.

    The result also carries the PREDICTED STEP TIME under the
    ``comm_cost.CommModelParams`` machine model (``params``, default
    ``resolve_comm_params()`` — calibrated via
    ``PADDLE_TPU_COMM_PARAMS``): ``max(compute_seconds,
    memory_seconds) + comm_seconds``, where the comm term prices every
    collective the placement table implies (ring alpha-beta model,
    ``comm_cost.program_comm_cost``). Without placements the comm term
    is zero — a single-chip replay has no collectives.

    ``op_calibration`` (an ``opprof.OpCalibration``, a dict/JSON/path,
    or None to consult ``PADDLE_TPU_OP_CALIBRATION``) applies
    measured correction factors from the op-level execution profiler:
    the whole-program FLOPs ratio scales ``flops``/``flops_by_prim``
    (tightening PTL302 drift), and per-prim time factors scale
    ``seconds_by_op`` — with factors fitted, the calibrated
    ``predicted_step_seconds`` is their sum, since the factors absorb
    the compute/memory overlap model (tightening PTL304). With no
    calibration resolvable the result is bit-identical to the
    uncalibrated model."""
    with _obs.span("cost.program_cost", histogram=M_ESTIMATE_SECONDS,
                   hist_labels={"kind": "flops"}):
        from .comm_cost import program_comm_cost, resolve_comm_params

        if placements is None and mesh is not None:
            from ...distributed.auto_parallel.completion import \
                complete_placements

            placements = complete_placements(program, mesh, {})
        avals = avals if avals is not None else propagate_avals(program)
        result = _program_cost(program, fetch, placements, avals)
        params = resolve_comm_params(params)
        flops_rate = params.resolved_flops_per_second()
        result.compute_seconds = result.flops / flops_rate
        result.memory_seconds = (result.bytes_read
                                 + result.bytes_written) \
            / params.hbm_bytes_per_second
        comm_by_op: Dict[int, float] = {}
        if placements:
            result.comm = program_comm_cost(
                program, placements, fetch=fetch, avals=avals,
                params=params)
            result.comm_seconds = result.comm.total_seconds
            comm_by_op = result.comm.seconds_by_op_index
        result.predicted_step_seconds = \
            max(result.compute_seconds, result.memory_seconds) \
            + result.comm_seconds
        result.seconds_by_op = [
            max(c.flops / flops_rate,
                c.bytes_total / params.hbm_bytes_per_second)
            + comm_by_op.get(i, 0.0)
            for i, c in enumerate(result.by_op)]
        cal = _resolve_op_calibration(op_calibration)
        if cal is not None and not cal.is_identity():
            if cal.flops_factor != 1.0:
                result.flops = int(round(result.flops
                                         * cal.flops_factor))
                result.flops_by_prim = {
                    k: int(round(v * cal.flops_factor))
                    for k, v in result.flops_by_prim.items()}
                result.compute_seconds = result.flops / flops_rate
                result.predicted_step_seconds = \
                    max(result.compute_seconds, result.memory_seconds) \
                    + result.comm_seconds
            if cal.factors:
                prims = [inst[0] for inst in program._insts]
                result.seconds_by_op = [
                    cal.factor(prims[i])
                    * max(c.flops / flops_rate,
                          c.bytes_total / params.hbm_bytes_per_second)
                    + comm_by_op.get(i, 0.0)
                    for i, c in enumerate(result.by_op)]
                # the measured factors already price the overlap the
                # max(compute, memory) model guesses at — the
                # calibrated step prediction is the attributed sum
                result.predicted_step_seconds = \
                    sum(result.seconds_by_op)
        return result


def _resolve_op_calibration(value):
    """Lazy bridge to ``observability.opprof.resolve_op_calibration``
    (None -> env -> identity); never raises — cost analysis must not
    fail because a calibration file is malformed."""
    try:
        from ...observability.opprof import resolve_op_calibration

        return resolve_op_calibration(value)
    except Exception:
        return None


def _program_cost(program, fetch, placements, avals) -> ProgramCost:
    avals = avals if avals is not None else propagate_avals(program)
    placements = placements or {}
    fetch_vids = _resolve_fetch_vids(program, fetch)
    insts = list(program._insts)
    kept = executed_op_indices(insts, fetch_vids) if fetch_vids \
        else set(range(len(insts)))

    result = ProgramCost()

    def aval_of(v):
        a = avals.get(v)
        if a is None:
            result.unknown_avals += 1
        return a

    def sharded_nbytes(v):
        return _nbytes(avals.get(v)) // _shard_divisor(placements.get(v))

    fwd_flops_live_to: Dict[int, int] = {}  # op idx -> flops (live ops)
    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(insts):
        if idx not in kept:
            result.by_op.append(OpCost())
            continue
        if prim_name == GRAD_OP:
            # jax.grad of the forward sub-replay live w.r.t. the loss:
            # the ops before this instruction that feed in_vids[0]
            loss_vid = in_vids[0] if in_vids else None
            sub = live_op_indices(insts[:idx], (loss_vid,)) \
                if loss_vid is not None else set()
            fwd = sum(fwd_flops_live_to.get(i, 0) for i in sub)
            flops = _GRAD_FLOPS_MULTIPLIER * fwd
            read = sum(sharded_nbytes(v) for v in in_vids)
            written = sum(sharded_nbytes(v) for v in out_vids)
            c = OpCost(flops=flops, bytes_read=read, bytes_written=written)
        else:
            try:
                attrs = dict(static_items)
            except (TypeError, ValueError):
                attrs = {}
            c = op_cost(prim_name, [aval_of(v) for v in in_vids],
                        [aval_of(v) for v in out_vids], attrs)
            if placements:
                out_specs = [placements.get(v) for v in out_vids]
                out_div = max((_compute_divisor(s) for s in out_specs),
                              default=1)
                out_div *= _contraction_divisor(
                    prim_name, attrs,
                    [placements.get(v) for v in in_vids], out_specs)
                c = OpCost(
                    flops=c.flops // out_div,
                    bytes_read=sum(sharded_nbytes(v) for v in in_vids),
                    bytes_written=sum(sharded_nbytes(v)
                                      for v in out_vids))
            # recorded AFTER the shard division: the backward is
            # partitioned like the forward, so the grad multiplier
            # must scale the per-chip count, not the global one
            fwd_flops_live_to[idx] = c.flops
        result.by_op.append(c)
        result.flops += c.flops
        result.bytes_read += c.bytes_read
        result.bytes_written += c.bytes_written
        result.flops_by_prim[prim_name] = \
            result.flops_by_prim.get(prim_name, 0) + c.flops
        result.live_ops += 1
    return result


def measure_program_flops(program, feed: Dict[str, np.ndarray],
                          fetch) -> int:
    """XLA compiled-cost-analysis FLOPs of THIS program's replay — the
    ground truth :func:`check_cost_model` compares the static estimate
    against. Pays one compile (of the same executable ``Executor.run``
    would build for this feed signature). Returns 0 when the backend
    reports no cost analysis."""
    from ...observability.runtime import measure_step_flops
    from ..program import Executor

    fetch_vids = _resolve_fetch_vids(program, fetch)
    feed_items = sorted(feed.items())
    feed_names = tuple(k for k, _ in feed_items)
    arrays = [np.asarray(v) for _, v in feed_items]
    fn = Executor._compile(program, feed_names, fetch_vids)
    return measure_step_flops(fn, *arrays)


#: per-code wiring for the drift check: (predicted gauge, measured
#: gauge, error gauge, unit rendered in the message, hint). PTL302 is
#: the FLOPs model vs XLA's compiled count; PTL304 is the step-time
#: model (compute + comm) vs measured train.step_seconds.
_DRIFT_CHECKS = {
    "PTL302": (M_PREDICTED_FLOPS, M_MEASURED_FLOPS, M_FLOPS_ERROR,
               "flops", "compiled cost analysis",
               "the per-op registry in static/analysis/cost.py no "
               "longer models what XLA executes — register/fix the "
               "drifting prim family (cost.model_flops_error_pct "
               "tracks the error per program)"),
    "PTL304": (M_PREDICTED_STEP, M_MEASURED_STEP, M_STEP_ERROR,
               "seconds", "measured train.step_seconds",
               "the step-time model (compute rate, HBM bandwidth or "
               "the comm alpha-beta fit) no longer matches what the "
               "hardware runs — recalibrate with "
               "tools/comm_calibrate.py or fix the drifting term "
               "(cost.model_step_error_pct tracks the error per "
               "program)"),
}


def check_cost_model(predicted: float, measured: float, *,
                     tolerance_pct: float = 25.0,
                     name: str = "program",
                     code: str = "PTL302") -> DiagnosticReport:
    """File ``code`` (**PTL302** FLOPs drift by default, **PTL304**
    step-time drift via :func:`check_step_time_model`) when the
    analytical estimate drifts more than ``tolerance_pct`` from its
    measured ground truth — the canary that catches cost-model rot (a
    new prim family the registry does not know, a changed lowering, a
    stale bandwidth calibration) before scheduling and placement
    decisions silently degrade. Both drift checks share THIS one
    implementation; only the gauges and the message differ. Records
    predicted/measured/error in the code's ``cost.*`` gauges; a
    measured value of 0 (backend without cost analysis, no step
    timings) is skipped, not flagged."""
    try:
        pred_g, meas_g, err_g, unit, truth, hint = _DRIFT_CHECKS[code]
    except KeyError:
        raise ValueError(
            f"check_cost_model knows {sorted(_DRIFT_CHECKS)}, "
            f"not {code!r}")
    report = DiagnosticReport()
    if measured <= 0:
        return report
    err_pct = abs(predicted - measured) / measured * 100
    if _obs.state.on:
        cast = int if unit == "flops" else float
        pred_g.set(cast(predicted), name=name)
        meas_g.set(cast(measured), name=name)
        err_g.set(round(err_pct, 2), name=name)
    if err_pct > tolerance_pct:
        fmt = (lambda v: f"{v:,.0f}") if unit == "flops" \
            else (lambda v: f"{v:.6f}")
        report.add(
            code, Severity.WARNING,
            f"cost model drift on {name!r}: analytical estimate "
            f"{fmt(predicted)} {unit} vs {truth} {fmt(measured)} "
            f"({err_pct:.1f}% > {tolerance_pct:.0f}% tolerance)",
            hint=hint)
    return report


def check_step_time_model(predicted_seconds: float,
                          measured_seconds: float, *,
                          tolerance_pct: float = 50.0,
                          name: str = "program") -> DiagnosticReport:
    """**PTL304**: the step-time twin of the PTL302 FLOPs check —
    predicted ``max(compute, memory) + comm`` vs the measured
    ``train.step_seconds`` mean. Same implementation
    (:func:`check_cost_model`), different code/gauges. The default
    tolerance is looser than PTL302's: wall time carries dispatch and
    allocator noise a FLOPs count does not."""
    return check_cost_model(predicted_seconds, measured_seconds,
                            tolerance_pct=tolerance_pct, name=name,
                            code="PTL304")
