"""Analytical communication cost model over a placement table.

Reference: the reference's auto_parallel cost layer prices collectives
analytically (auto_parallel/static/cost/comm_op_cost.py —
``AllreduceSumOpCost``/``IdentityOpCost`` with alpha/beta ring terms)
and the planner searches shard plans against that model. Here the same
layer lands on the flat ``Program`` instruction list + the
``DistTensorSpec`` placement tables the completion pass derives:

1. **Per-collective price** (:func:`collective_cost`): standard ring
   formulas over a mesh group of ``n`` chips, with the payload defined
   as the FULL (unsharded) logical tensor bytes:

   - all-reduce       wire = 2(n-1)/n * payload,  n-1 + n-1 hops
   - all-gather       wire =  (n-1)/n * payload,  n-1 hops
   - reduce-scatter   wire =  (n-1)/n * payload,  n-1 hops
   - all-to-all       wire = (n-1)/n^2 * payload, n-1 hops
   - broadcast        wire =            payload,  n-1 hops
   - p2p              wire =            payload,  1 hop

   ``seconds = wire / link_bandwidth + hops * link_latency`` — the
   alpha-beta model every collective paper and the reference's
   CommOpCost use. ``n <= 1`` prices to zero (single-chip groups are
   free by construction, same as the runtime collectives).

2. **Which collectives a placement implies**
   (:func:`derive_collectives`): walk the instruction list the way
   ``sharding_lint.run_placement_lints`` does, but price BOTH the
   legitimate collectives a consistent plan needs (matching
   contracting-dim shards -> one psum per GEMM; data-parallel gradient
   all-reduce at the ``__gradients__`` boundary) AND the avoidable ones
   the PTL202 lint flags (contracting mismatch -> all-gather, layout
   conflict -> resharding all-to-all, Partial consumed early ->
   materializing all-reduce). The contracting-dim definition is shared
   with the lint (``sharding_lint.matmul_contracting_dims``) so the
   model can never price a different collective than the lint flags.

3. **Calibration** (:func:`calibrate_comm_model`): least-squares
   alpha-beta fit from the PR 5 ``comm.collective_calls/_bytes/
   _seconds`` telemetry in a metrics dump — measured wall time per
   (op, group) series regressed on calls (latency term) and bytes
   (bandwidth term). ``PADDLE_TPU_COMM_PARAMS`` (inline JSON or a JSON
   file path, written by ``tools/comm_calibrate.py``) feeds the fitted
   parameters back into :func:`resolve_comm_params`, where
   ``program_cost`` picks them up.

``cost.program_cost(prog, placements=..., mesh=...)`` composes this
with the PR 15 compute/bytes model into a full predicted step time
``max(compute_seconds, memory_seconds) + comm_seconds`` — the number
the auto-sharding search in ``auto_parallel/completion.py`` ranks
plans by, and that PTL304 validates against measured
``train.step_seconds``.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ... import observability as _obs
from .cost import Aval, _nbytes, _resolve_fetch_vids, executed_op_indices
from .sharding_lint import _MATMUL_PRIMS, _REDUCING_MARKERS, _elementwise, \
    _partial_axes, _shard_axes, matmul_contracting_dims
from .verify import GRAD_OP, propagate_avals

__all__ = [
    "CommModelParams", "Collective", "CommCostResult", "COMM_PARAMS_ENV",
    "collective_cost", "derive_collectives", "program_comm_cost",
    "resolve_comm_params", "calibrate_comm_model", "COLLECTIVE_KINDS",
]

#: env feed for calibrated parameters: inline JSON or a path to a JSON
#: file holding {"link_bytes_per_second": ..., ...} — written by
#: tools/comm_calibrate.py, read by resolve_comm_params() and therefore
#: by every program_cost/search call that does not pass params=.
COMM_PARAMS_ENV = "PADDLE_TPU_COMM_PARAMS"

#: collective kinds the model prices (the ``kind`` vocabulary of
#: :class:`Collective` and the per-kind tables in CommCostResult).
COLLECTIVE_KINDS = ("all_reduce", "all_gather", "reduce_scatter",
                    "all_to_all", "broadcast", "p2p")

M_COMM_PREDICTED_BYTES = _obs.gauge(
    "cost.comm_predicted_bytes",
    "analytical per-chip wire bytes the placement table implies for a "
    "program replay, by program name and collective kind")
M_COMM_PREDICTED_SECONDS = _obs.gauge(
    "cost.comm_predicted_seconds",
    "analytical communication seconds (ring alpha-beta model) the "
    "placement table implies for a program replay, by program name and "
    "collective kind")


@dataclass(frozen=True)
class CommModelParams:
    """Alpha-beta machine model for the step-time prediction.

    Defaults are v5e-shaped nominal figures (1 ICI link ~45 GB/s on
    the 2D torus -> ~9e10 effective with both directions; ~1 us per
    hop; HBM ~819 GB/s; peak FLOPs from the same ladder MFU uses) —
    honest enough for RANKING placements out of the box, and
    :func:`calibrate_comm_model` replaces them with measured fits."""

    link_bytes_per_second: float = 9e10
    link_latency_seconds: float = 1e-6
    flops_per_second: float = 0.0   # 0 -> default_peak_flops() ladder
    hbm_bytes_per_second: float = 8.1e11

    def resolved_flops_per_second(self) -> float:
        if self.flops_per_second > 0:
            return self.flops_per_second
        from ...observability.runtime import default_peak_flops

        return float(default_peak_flops())

    def to_dict(self) -> Dict[str, float]:
        return {
            "link_bytes_per_second": self.link_bytes_per_second,
            "link_latency_seconds": self.link_latency_seconds,
            "flops_per_second": self.flops_per_second,
            "hbm_bytes_per_second": self.hbm_bytes_per_second,
        }


def resolve_comm_params(params: Optional[CommModelParams] = None
                        ) -> CommModelParams:
    """``params`` if given, else the ``PADDLE_TPU_COMM_PARAMS`` env
    override (inline JSON or a JSON file path — unknown keys ignored,
    so a dump written by a newer tool still loads), else defaults."""
    if params is not None:
        return params
    env = os.environ.get(COMM_PARAMS_ENV)
    if not env:
        return CommModelParams()
    try:
        if env.lstrip().startswith("{"):
            d = json.loads(env)
        else:
            with open(env) as f:
                d = json.load(f)
        fields_ = CommModelParams().to_dict()
        return CommModelParams(**{k: float(v) for k, v in d.items()
                                  if k in fields_})
    except (OSError, ValueError, TypeError):
        return CommModelParams()


# ring wire-traffic fraction of the full payload, and hop count, by kind
def _ring_terms(kind: str, n: int) -> Tuple[float, int]:
    if kind == "all_reduce":
        return 2.0 * (n - 1) / n, 2 * (n - 1)
    if kind in ("all_gather", "reduce_scatter"):
        return (n - 1) / n, n - 1
    if kind == "all_to_all":
        return (n - 1) / (n * n), n - 1
    if kind == "broadcast":
        return 1.0, n - 1
    if kind == "p2p":
        return 1.0, 1
    raise ValueError(f"unknown collective kind {kind!r} "
                     f"(known: {COLLECTIVE_KINDS})")


def collective_cost(kind: str, payload_bytes: int, group_size: int,
                    params: Optional[CommModelParams] = None
                    ) -> Tuple[int, float]:
    """(per-chip wire bytes, seconds) of one collective over a group of
    ``group_size`` chips, ``payload_bytes`` being the FULL unsharded
    logical tensor (ring formulas in the module docstring). A group of
    one chip is free — XLA elides the collective entirely."""
    n = int(group_size)
    if n <= 1 or payload_bytes <= 0:
        return 0, 0.0
    params = resolve_comm_params(params)
    frac, hops = _ring_terms(kind, n)
    wire = int(payload_bytes * frac)
    seconds = wire / params.link_bytes_per_second \
        + hops * params.link_latency_seconds
    return wire, seconds


@dataclass(frozen=True)
class Collective:
    """One collective a placement table implies for one instruction."""

    kind: str                  # one of COLLECTIVE_KINDS
    op_index: int              # instruction that forces it
    vid: int                   # the value moved/reduced
    payload_bytes: int         # full logical tensor bytes
    group_size: int            # chips in the group (mesh-axes product)
    mesh_axes: Tuple[int, ...] # mesh axes the group spans
    reason: str                # human-readable why
    wire_bytes: int = 0        # per-chip ring traffic (priced)
    seconds: float = 0.0       # alpha-beta model seconds (priced)


@dataclass
class CommCostResult:
    """All collectives one (program, placements) pair implies, priced."""

    collectives: List[Collective] = field(default_factory=list)
    bytes_by_kind: Dict[str, int] = field(default_factory=dict)
    seconds_by_kind: Dict[str, float] = field(default_factory=dict)
    seconds_by_op_index: Dict[int, float] = field(default_factory=dict)
    total_bytes: int = 0
    total_seconds: float = 0.0
    params: CommModelParams = field(default_factory=CommModelParams)

    def render(self) -> str:
        per = ", ".join(
            f"{k}={self.bytes_by_kind[k]:,}B/"
            f"{self.seconds_by_kind[k] * 1e6:.1f}us"
            for k in sorted(self.bytes_by_kind))
        return (f"comm cost: {len(self.collectives)} collective(s), "
                f"{self.total_bytes:,} wire B, "
                f"{self.total_seconds * 1e6:.1f}us ({per or 'none'})")


def _group(spec, axes: Iterable[int]) -> Tuple[Tuple[int, ...], int]:
    axes = tuple(sorted(set(axes)))
    n = 1
    for a in axes:
        n *= int(spec.mesh.shape[a])
    return axes, n


def _shard_divisor_excluding(spec, excluded: Iterable[int]) -> int:
    """Bytes divisor counting Shard axes OUTSIDE ``excluded`` — the
    per-chip size of a value whose ``excluded`` axes the collective is
    about to traverse (those axes' sharding IS the payload split the
    ring formula already accounts for)."""
    if spec is None:
        return 1
    excluded = set(excluded)
    div = 1
    for axis, p in enumerate(spec.placements):
        if p.is_shard() and axis not in excluded:
            div *= int(spec.mesh.shape[axis])
    return max(div, 1)


def derive_collectives(prog, placements: Dict[int, Any],
                       fetch=None,
                       avals: Optional[Dict[int, Aval]] = None
                       ) -> List[Collective]:
    """The collectives ``placements`` implies for ``prog``'s live ops —
    unpriced (:func:`program_comm_cost` adds wire bytes + seconds).

    Walks the instruction list exactly like
    ``sharding_lint.run_placement_lints`` (same matmul prim set, same
    shared contracting-dim helper, same elementwise family, same
    reducing-consumer markers) and emits:

    - matching contracting-dim shards on a matmul -> ONE combine of the
      output over those axes: ``reduce_scatter`` when the output spec
      keeps a Shard on a contracting mesh axis, ``all_reduce``
      otherwise; skipped while the output spec still says Partial there
      (the psum is deferred until a non-reducing consumer forces it —
      priced by the Partial walk below, at the consumer);
    - MISmatched contracting shards -> ``all_gather`` of each operand
      whose extra axes the partitioner must unshard (the avoidable
      collective PTL202 flags);
    - conflicting elementwise layouts -> resharding ``all_to_all`` of
      the later operand (PTL202's other family);
    - a Partial value consumed by a non-reducing op -> materializing
      ``all_reduce`` over its partial axes, charged ONCE per value (it
      materializes once, then every later consumer reads the result);
    - the ``__gradients__`` boundary -> data-parallel gradient
      ``all_reduce`` of each grad output over the mesh axes that shard
      a data placeholder but not the grad itself.
    """
    avals = avals if avals is not None else propagate_avals(prog)
    insts = list(prog._insts)
    fetch_vids = _resolve_fetch_vids(prog, fetch)
    kept = executed_op_indices(insts, fetch_vids) if fetch_vids \
        else set(range(len(insts)))

    # mesh axes that shard any data placeholder = the dp-like axes whose
    # per-chip grads differ and need the gradient psum
    data_axes: set = set()
    for _name, vid, _shape, _dtype in prog._placeholders:
        s = placements.get(vid)
        if s is not None:
            for axis, p in enumerate(s.placements):
                if p.is_shard():
                    data_axes.add(axis)

    out: List[Collective] = []
    materialized: set = set()  # vids whose Partial psum is already charged

    def payload(vid, spec, traversed_axes) -> int:
        return _nbytes(avals.get(vid)) \
            // _shard_divisor_excluding(spec, traversed_axes)

    for idx, (prim_name, in_vids, static_items, out_vids) in \
            enumerate(insts):
        if idx not in kept:
            continue
        try:
            attrs = dict(static_items)
        except (TypeError, ValueError):
            attrs = {}

        if prim_name == GRAD_OP:
            for gv in out_vids:
                gs = placements.get(gv)
                axes = sorted(
                    a for a in data_axes
                    if gs is None or not (gs.placements[a].is_shard()
                                          or gs.placements[a].is_partial()))
                if not axes:
                    continue
                ref = gs if gs is not None else next(
                    iter(placements.values()), None)
                if ref is None:
                    continue
                gaxes, n = _group(ref, axes)
                if n <= 1:
                    continue
                out.append(Collective(
                    "all_reduce", idx, gv,
                    payload(gv, gs, gaxes), n, gaxes,
                    "data-parallel gradient all-reduce (grad replicated "
                    "on a mesh axis that shards the data)"))
            continue

        # Partial consumed by a non-reducing op: the pending psum
        # materializes here (charged once per value)
        if not any(m in prim_name.lower() for m in _REDUCING_MARKERS):
            for v in in_vids:
                s = placements.get(v)
                if s is None or v in materialized:
                    continue
                paxes = _partial_axes(s)
                if not paxes:
                    continue
                materialized.add(v)
                gaxes, n = _group(s, paxes)
                out.append(Collective(
                    "all_reduce", idx, v, payload(v, s, gaxes), n, gaxes,
                    "Partial value materialized by a non-reducing "
                    "consumer"))

        if prim_name in _MATMUL_PRIMS and len(in_vids) >= 2:
            x = placements.get(in_vids[0])
            w = placements.get(in_vids[1])
            if x is not None and w is not None and x.ndim >= 1 \
                    and w.ndim >= 1:
                x_c, w_c = matmul_contracting_dims(attrs, x.ndim, w.ndim)
                ax_x = set(_shard_axes(x, x_c))
                ax_w = set(_shard_axes(w, w_c))
                shared = ax_x & ax_w
                if shared and out_vids:
                    ov = out_vids[0]
                    os_ = placements.get(ov)
                    partial_there = os_ is not None and any(
                        a in shared for a in _partial_axes(os_))
                    if not partial_there:
                        gaxes, n = _group(x, shared)
                        shard_there = os_ is not None and any(
                            os_.placements[a].is_shard() for a in shared)
                        kind = "reduce_scatter" if shard_there \
                            else "all_reduce"
                        out.append(Collective(
                            kind, idx, ov, payload(ov, os_, gaxes), n,
                            gaxes,
                            "contraction split over the mesh: one psum "
                            "combines the per-chip partial GEMMs"))
                for vid_o, spec_o, extra in (
                        (in_vids[0], x, ax_x - ax_w),
                        (in_vids[1], w, ax_w - ax_x)):
                    if not extra:
                        continue
                    gaxes, n = _group(spec_o, extra)
                    if n <= 1:
                        continue
                    out.append(Collective(
                        "all_gather", idx, vid_o,
                        payload(vid_o, spec_o, gaxes), n, gaxes,
                        "contracting dim sharded on one operand only: "
                        "the partitioner must allgather it before the "
                        "contraction (PTL202)"))
            continue

        if not _elementwise(prim_name):
            continue
        known = [(v, placements.get(v)) for v in in_vids
                 if placements.get(v) is not None
                 and v not in prog._consts]
        for i in range(len(known)):
            for j in range(i + 1, len(known)):
                (_va, sa), (vb, sb) = known[i], known[j]
                if sa.shape != sb.shape or sa.ndim == 0:
                    continue
                conflict_axes: set = set()
                for d in range(sa.ndim):
                    axa, axb = _shard_axes(sa, d), _shard_axes(sb, d)
                    if axa and axb and set(axa) != set(axb):
                        conflict_axes = set(axa) | set(axb)
                        break
                if not conflict_axes:
                    ma = {a: d for d in range(sa.ndim)
                          for a in _shard_axes(sa, d)}
                    mb = {a: d for d in range(sb.ndim)
                          for a in _shard_axes(sb, d)}
                    for a in sorted(set(ma) & set(mb)):
                        if ma[a] != mb[a]:
                            conflict_axes = {a}
                            break
                if conflict_axes:
                    gaxes, n = _group(sb, conflict_axes)
                    if n > 1:
                        out.append(Collective(
                            "all_to_all", idx, vb,
                            payload(vb, sb, gaxes), n, gaxes,
                            "conflicting elementwise layouts: one "
                            "operand resharded before the op (PTL202)"))
                    break  # one reshard fixes this operand pair set
    return out


def program_comm_cost(prog, placements: Dict[int, Any], *,
                      fetch=None,
                      avals: Optional[Dict[int, Aval]] = None,
                      params: Optional[CommModelParams] = None
                      ) -> CommCostResult:
    """Derive + price every collective ``placements`` implies for the
    live ops of ``prog``: the comm half of the predicted step time
    ``cost.program_cost`` returns."""
    params = resolve_comm_params(params)
    result = CommCostResult(params=params)
    for c in derive_collectives(prog, placements, fetch=fetch,
                                avals=avals):
        wire, seconds = collective_cost(
            c.kind, c.payload_bytes, c.group_size, params)
        c = replace(c, wire_bytes=wire, seconds=seconds)
        result.collectives.append(c)
        result.bytes_by_kind[c.kind] = \
            result.bytes_by_kind.get(c.kind, 0) + wire
        result.seconds_by_kind[c.kind] = \
            result.seconds_by_kind.get(c.kind, 0.0) + seconds
        result.seconds_by_op_index[c.op_index] = \
            result.seconds_by_op_index.get(c.op_index, 0.0) + seconds
        result.total_bytes += wire
        result.total_seconds += seconds
    return result


def record_comm_cost(result: CommCostResult, name: str) -> None:
    """Publish a CommCostResult to the ``cost.comm_predicted_*`` gauges
    (by program name + collective kind, with an ``all`` roll-up kind),
    where the report tables and the bench roll-up read them."""
    if not _obs.state.on:
        return
    for kind in result.bytes_by_kind:
        M_COMM_PREDICTED_BYTES.set(int(result.bytes_by_kind[kind]),
                                   name=name, kind=kind)
        M_COMM_PREDICTED_SECONDS.set(
            round(result.seconds_by_kind[kind], 9), name=name, kind=kind)
    M_COMM_PREDICTED_BYTES.set(int(result.total_bytes), name=name,
                               kind="all")
    M_COMM_PREDICTED_SECONDS.set(round(result.total_seconds, 9),
                                 name=name, kind="all")


# ---------------------------------------------------------------------------
# calibration from telemetry
# ---------------------------------------------------------------------------

def _metric_series(metrics: Dict[str, Any], name: str) -> List[dict]:
    m = metrics.get(name) or {}
    return list(m.get("series") or [])


def calibrate_comm_model(metrics: Dict[str, Any],
                         base: Optional[CommModelParams] = None
                         ) -> CommModelParams:
    """Alpha-beta fit from ``comm.collective_*`` telemetry in a metrics
    dump (the dict ``observability.dump()`` writes, or its inner
    ``metrics`` mapping).

    Per (op, group) series the runtime recorded ``calls`` invocations
    moving ``bytes`` payload in ``seconds`` total wall time; least
    squares over the series solves ``seconds = alpha * calls +
    bytes / beta`` — alpha lands in ``link_latency_seconds`` (per-call
    launch+hop latency) and beta in ``link_bytes_per_second``
    (effective achieved bandwidth, ring factor absorbed, which is
    exactly what the predictor wants since it prices wire bytes with
    the same ring fractions the runtime paid). Degenerate inputs
    (no series, zero bytes, singular normal equations) keep the
    ``base`` / default parameters for the missing term rather than
    inventing one. Fits are clamped non-negative."""
    if "metrics" in metrics and isinstance(metrics.get("metrics"), dict):
        metrics = metrics["metrics"]
    base = base or CommModelParams()

    calls_by = {tuple(sorted((s.get("labels") or {}).items())):
                float(s.get("value", 0))
                for s in _metric_series(metrics, "comm.collective_calls")}
    bytes_by = {tuple(sorted((s.get("labels") or {}).items())):
                float(s.get("value", 0))
                for s in _metric_series(metrics, "comm.collective_bytes")}
    pts: List[Tuple[float, float, float]] = []   # (calls, bytes, seconds)
    for s in _metric_series(metrics, "comm.collective_seconds"):
        key = tuple(sorted((s.get("labels") or {}).items()))
        secs = float(s.get("sum", 0.0) or 0.0)
        c = calls_by.get(key, float(s.get("count", 0) or 0))
        b = bytes_by.get(key, 0.0)
        if c > 0 and secs > 0:
            pts.append((c, b, secs))
    if not pts:
        return base

    # normal equations for seconds = alpha*calls + gamma*bytes
    scc = sum(c * c for c, _b, _s in pts)
    sbb = sum(b * b for _c, b, _s in pts)
    scb = sum(c * b for c, b, _s in pts)
    scs = sum(c * s for c, _b, s in pts)
    sbs = sum(b * s for _c, b, s in pts)
    det = scc * sbb - scb * scb
    alpha = gamma = None
    if sbb > 0 and abs(det) > 1e-12 * max(scc * sbb, 1.0):
        alpha = (scs * sbb - sbs * scb) / det
        gamma = (scc * sbs - scb * scs) / det
    if gamma is None or gamma <= 0:
        # bandwidth-only fallback: all measured seconds charged to bytes
        total_b = sum(b for _c, b, _s in pts)
        total_s = sum(s for _c, _b, s in pts)
        gamma = total_s / total_b if total_b > 0 else None
        alpha = None
    if alpha is None or alpha < 0:
        # latency fallback: residual seconds per call after the
        # bandwidth term (non-negative by clamping)
        total_c = sum(c for c, _b, _s in pts)
        resid = sum(s - (gamma or 0.0) * b for _c, b, s in pts)
        alpha = max(resid / total_c, 0.0) if total_c > 0 else \
            base.link_latency_seconds
    return CommModelParams(
        link_bytes_per_second=(1.0 / gamma) if gamma and gamma > 0
        else base.link_bytes_per_second,
        link_latency_seconds=alpha,
        flops_per_second=base.flops_per_second,
        hbm_bytes_per_second=base.hbm_bytes_per_second)


def calibrate_step_time_model(metrics: Dict[str, Any],
                              predicted_flops: float,
                              base: Optional[CommModelParams] = None
                              ) -> CommModelParams:
    """Extend :func:`calibrate_comm_model` with a compute-rate fit:
    achieved ``flops_per_second = predicted_flops / mean
    train.step_seconds`` from the same dump — the single-program
    calibration the CPU-bound test suite needs (XLA:CPU achieves a few
    GF/s, nowhere near any nominal peak), and a no-op when the dump has
    no step timings."""
    params = calibrate_comm_model(metrics, base=base)
    m = metrics.get("metrics") if isinstance(metrics.get("metrics"), dict) \
        else metrics
    for s in _metric_series(m or {}, "train.step_seconds"):
        cnt = float(s.get("count", 0) or 0)
        tot = float(s.get("sum", 0.0) or 0.0)
        if cnt > 0 and tot > 0 and predicted_flops > 0:
            return replace(params,
                           flops_per_second=predicted_flops / (tot / cnt))
    return params
