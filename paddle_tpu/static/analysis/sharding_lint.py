"""Sharding-aware lints (``PTL2xx``): layout findings that feed the
auto-parallel planner and the fleet-telemetry plane.

Three lints, three inputs:

- PTL201 (lint.py, runs under plain ``run_lints``): fp32 operands on a
  bf16 compute hot path — dtype is part of layout, no mesh needed.
- PTL202 (:func:`run_placement_lints`): a program plus a placement plan
  (``vid -> DistTensorSpec``, either given or derived via
  ``auto_parallel.completion.complete_placements``) — flags operand
  placements that force a collective a consistent plan avoids:
  mismatched contracting-dim sharding on matmuls, conflicting shard
  axes on elementwise operands, Partial values consumed by
  non-reducing ops.
- PTL203 (:func:`lint_fleet_trace`): the PR 8 merged fleet timeline
  (``fleet_trace.json`` — one process lane per rank, spans for events
  carrying a duration) — flags collective spans that do not overlap
  any compute span on their rank, i.e. collectives the schedule
  serializes against compute instead of hiding behind it. The
  straggler detector's per-rank ``train.step_seconds`` spread is the
  runtime confirmation that the exposed latency is real.

All three funnel into the same :class:`DiagnosticReport` type as the
program lints, so codes/severities/rendering are uniform.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .diagnostics import DiagnosticReport, Severity

__all__ = ["run_placement_lints", "lint_fleet_trace",
           "apply_placement_suggestion", "matmul_contracting_dims",
           "SHARDING_LINT_CODES"]

#: codes this module can emit — audited by tools/lint_registry.py the
#: same way lint.LINTS codes are (every code claimed in CODES, every
#: code exercised by at least one test).
SHARDING_LINT_CODES = ("PTL202", "PTL203")

# prims that REDUCE their input — a Partial operand feeding one of
# these folds into the reduction instead of forcing an allreduce first
_REDUCING_MARKERS = ("reduce", "sum", "mean", "norm", "softmax", "logsumexp")

_MATMUL_PRIMS = ("matmul", "linear_nobias_p", "linear_p", "bmm")

# binary ops whose operand dims ARE aligned 1:1 — only for these does
# "same dim sharded on different axes" mean a forced reshard. Anything
# else (conv, einsum, gather, concat) relates operand dims semantically
# and must not be judged by pairwise dim alignment.
_ELEMENTWISE_NAMES = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "pow", "elementwise_pow", "mod", "remainder", "floor_divide",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_xor",
    "atan2", "hypot", "fmax", "fmin",
})


def _elementwise(prim_name: str) -> bool:
    return prim_name.removesuffix("_p") in _ELEMENTWISE_NAMES


def _shard_axes(spec, tensor_dim: int) -> List[int]:
    """Mesh axes on which ``tensor_dim`` of ``spec`` is sharded."""
    return [a for a, p in enumerate(spec.placements)
            if p.is_shard(tensor_dim)]


def _partial_axes(spec) -> List[int]:
    return [a for a, p in enumerate(spec.placements) if p.is_partial()]


def matmul_contracting_dims(attrs: Dict[str, Any], x_ndim: int,
                            w_ndim: int) -> tuple:
    """(x_contracting_dim, w_contracting_dim) for a matmul-family prim,
    honoring its ``transpose_x``/``transpose_y`` static attrs — the ONE
    definition shared by the PTL202 lint and the comm cost model
    (``static/analysis/comm_cost.py``), so "which dims contract" can
    never diverge between the lint that flags a mismatch and the model
    that prices the collective it forces."""
    tx = bool(attrs.get("transpose_x", False))
    ty = bool(attrs.get("transpose_y", False))
    x_c = x_ndim - 2 if (tx and x_ndim >= 2) else x_ndim - 1
    if w_ndim >= 2:
        w_c = w_ndim - 1 if ty else w_ndim - 2
    else:
        w_c = 0
    return x_c, w_c


def _suggest(kind: str, op_index: int, vid: int, dim: Optional[int],
             mesh_axis: Optional[int], placement: str) -> Dict[str, Any]:
    """Machine-readable PTL202 re-placement payload — the interface the
    ``PADDLE_TPU_REPLACEMENT`` hook in auto_parallel/completion.py
    consumes. Plain JSON-able values only: ``placement`` is "shard"
    (put ``dim`` of ``vid`` on ``mesh_axis``) or "replicate" (clear the
    conflicting shard of ``dim`` — dim None clears everything,
    including Partial)."""
    return {"kind": kind, "op_index": op_index, "vid": vid, "dim": dim,
            "mesh_axis": mesh_axis, "placement": placement}


def _align_suggestion(kind, idx, vid, spec, dim, target_axes
                      ) -> Dict[str, Any]:
    """Suggest re-placing ``dim`` of ``vid`` onto ``target_axes`` (the
    other operand's layout): shard when a target axis exists and the
    dim divides it, else replicate the dim."""
    axes = sorted(target_axes)
    if axes and spec.shape[dim] % int(spec.mesh.shape[axes[0]]) == 0:
        return _suggest(kind, idx, vid, dim, axes[0], "shard")
    return _suggest(kind, idx, vid, dim, None, "replicate")


def apply_placement_suggestion(spec, suggestion):
    """Return a NEW DistTensorSpec with one PTL202 ``suggestion``
    payload applied to ``spec`` (shared by tests and the completion
    hook, so "what applying a suggestion means" has one definition).

    "shard": clear every axis currently sharding ``dim`` (and any
    Partial), then put ``Shard(dim)`` on ``mesh_axis`` — axes sharding
    OTHER dims are untouched unless ``mesh_axis`` collides, in which
    case the suggestion wins. "replicate": clear shards of ``dim``
    (``dim`` None clears every shard and Partial)."""
    from ...distributed.auto_parallel.placement import Replicate, Shard
    from ...distributed.auto_parallel.spmd_rules import DistTensorSpec

    dim = suggestion.get("dim")
    placements = list(spec.placements)
    for axis, p in enumerate(placements):
        if p.is_partial():
            placements[axis] = Replicate()
        elif dim is None and p.is_shard():
            placements[axis] = Replicate()
        elif dim is not None and p.is_shard(dim):
            placements[axis] = Replicate()
    if suggestion.get("placement") == "shard" and dim is not None \
            and suggestion.get("mesh_axis") is not None:
        placements[int(suggestion["mesh_axis"])] = Shard(int(dim))
    return DistTensorSpec(list(spec.shape), spec.mesh, placements)


def run_placement_lints(prog, mesh=None, placements=None,
                        seeds=None) -> DiagnosticReport:
    """PTL202 over one program + placement plan.

    ``placements`` is a ``vid -> DistTensorSpec`` table; when omitted it
    is derived with ``complete_placements(prog, mesh, seeds or {})``
    (``mesh`` is then required)."""
    report = DiagnosticReport()
    if placements is None:
        if mesh is None:
            raise ValueError(
                "run_placement_lints needs either a placements table or "
                "a mesh to derive one from")
        from ...distributed.auto_parallel.completion import \
            complete_placements

        placements = complete_placements(prog, mesh, dict(seeds or {}))

    for idx, (prim_name, in_vids, static_items, _out_vids) in \
            enumerate(prog._insts):
        specs = [(v, placements.get(v)) for v in in_vids]
        try:
            attrs = dict(static_items)
        except (TypeError, ValueError):
            attrs = {}

        # Partial consumed by a non-reducing op: the pending psum must
        # materialize RIGHT HERE — an allreduce a reduction-aware
        # placement (or deferring the consumer) avoids
        if not any(m in prim_name.lower() for m in _REDUCING_MARKERS):
            for v, s in specs:
                if s is not None and _partial_axes(s):
                    report.add(
                        "PTL202", Severity.WARNING,
                        f"{prim_name!r} consumes %{v} while it is still "
                        f"partial on mesh axes {_partial_axes(s)} — forces "
                        f"an allreduce before this op", op_index=idx,
                        hint="let a reducing consumer absorb the partial "
                             "sum, or re-place the producer so its output "
                             "is sharded instead of partial",
                        suggestion=_suggest("partial_consumed", idx, v,
                                            None, None, "replicate"))

        if prim_name in _MATMUL_PRIMS and len(in_vids) >= 2:
            x, w = specs[0][1], specs[1][1]
            if x is not None and w is not None and x.ndim >= 1 \
                    and w.ndim >= 1:
                # contracting dims, honoring the matmul prim's
                # transpose_x/transpose_y static attrs
                x_c, w_c = matmul_contracting_dims(attrs, x.ndim, w.ndim)
                ax_x = set(_shard_axes(x, x_c))
                ax_w = set(_shard_axes(w, w_c))
                if ax_x != ax_w:
                    report.add(
                        "PTL202", Severity.WARNING,
                        f"{prim_name!r}: contracting dims are sharded "
                        f"inconsistently (%{in_vids[0]} dim {x_c} on mesh "
                        f"axes {sorted(ax_x)}, %{in_vids[1]} dim {w_c} on "
                        f"{sorted(ax_w)}) — the partitioner must allgather "
                        f"or reshard one operand before the contraction",
                        op_index=idx,
                        hint="shard both contracting dims on the same mesh "
                             "axis (classic row/column-parallel pairing); "
                             "the psum then happens once, after the GEMM",
                        # align the weight side to the activation side:
                        # activation layouts are usually pinned by the
                        # surrounding plan, weight placements are free
                        suggestion=_align_suggestion(
                            "matmul_contracting", idx, in_vids[1], w,
                            w_c, ax_x))
            continue

        # elementwise family ONLY: same-shape operands whose shard
        # layouts conflict (same tensor dim on different axes, or one
        # mesh axis sharding different dims) force a reshard of one
        if not _elementwise(prim_name):
            continue
        known = [(v, s) for v, s in specs
                 if s is not None and v not in prog._consts]
        for i in range(len(known)):
            for j in range(i + 1, len(known)):
                (va, sa), (vb, sb) = known[i], known[j]
                if sa.shape != sb.shape or sa.ndim == 0:
                    continue
                conflict = None
                cdim = 0  # the dim of %vb the suggestion re-places
                for d in range(sa.ndim):
                    axa, axb = _shard_axes(sa, d), _shard_axes(sb, d)
                    if axa and axb and set(axa) != set(axb):
                        conflict = (f"dim {d} sharded on mesh axes "
                                    f"{axa} vs {axb}")
                        cdim = d
                        break
                if conflict is None:
                    ma = {a: d for d in range(sa.ndim)
                          for a in _shard_axes(sa, d)}
                    mb = {a: d for d in range(sb.ndim)
                          for a in _shard_axes(sb, d)}
                    for a in sorted(set(ma) & set(mb)):
                        if ma[a] != mb[a]:
                            conflict = (f"mesh axis {a} shards dim "
                                        f"{ma[a]} vs dim {mb[a]}")
                            cdim = mb[a]
                            break
                if conflict:
                    # align the later operand to the earlier one (the
                    # earlier producer's layout is upstream context)
                    report.add(
                        "PTL202", Severity.WARNING,
                        f"{prim_name!r}: operands %{va} and %{vb} have "
                        f"conflicting layouts ({conflict}) — one must be "
                        f"resharded (all-to-all/allgather) before the op",
                        op_index=idx,
                        hint="re-place one producer so the layouts agree; "
                             "an aligned plan makes this op collective-free",
                        suggestion=_align_suggestion(
                            "elementwise_conflict", idx, vb, sb, cdim,
                            _shard_axes(sa, cdim)))
    return report


def _trace_events(trace) -> List[Dict[str, Any]]:
    if isinstance(trace, dict):
        return list(trace.get("traceEvents", []))
    return list(trace or [])


def _is_comm(name: str) -> bool:
    return name.startswith("comm.")


#: whole-step envelope spans (``obs.step_region``): they CONTAIN every
#: in-step collective, so they only serve as the compute baseline when
#: no finer-grained compute spans exist on the lane — otherwise every
#: collective would trivially "overlap compute" and the lint would
#: never fire on a real fleet trace.
_ENVELOPE_NAMES = ("train.step", "train.epoch")


def _is_envelope(name: str) -> bool:
    return name in _ENVELOPE_NAMES


def lint_fleet_trace(trace, *, min_seconds: float = 0.0
                     ) -> DiagnosticReport:
    """PTL203 over a merged fleet Chrome trace (dict with
    ``traceEvents`` or a bare event list).

    A collective span (name prefixed ``comm.``) on a rank lane that
    overlaps NO compute span is exposed latency: the schedule runs the
    collective serially instead of hiding it behind compute. Compute
    spans are the lane's non-collective spans — preferring spans finer
    than the whole-step ``train.step`` envelope when any exist (an
    envelope contains every in-step collective, so against it only
    BETWEEN-step collectives can be caught). Ranks with no compute
    spans at all are skipped — that is missing data, not a finding."""
    report = DiagnosticReport()
    spans: Dict[Any, List[tuple]] = {}
    for e in _trace_events(trace):
        if e.get("ph") != "X":
            continue
        dur = float(e.get("dur") or 0.0)
        if dur <= 0:
            continue
        ts = float(e.get("ts") or 0.0)
        spans.setdefault(e.get("pid"), []).append(
            (str(e.get("name", "")), ts, ts + dur))
    for rank in sorted(spans, key=str):
        comm = [s for s in spans[rank] if _is_comm(s[0])]
        non_comm = [s for s in spans[rank] if not _is_comm(s[0])]
        compute = [s for s in non_comm if not _is_envelope(s[0])] \
            or non_comm
        if not comm or not compute:
            continue  # nothing to attribute on this lane
        for name, t0, t1 in comm:
            if (t1 - t0) / 1e6 < min_seconds:
                continue
            if any(min(t1, c1) - max(t0, c0) > 0
                   for _n, c0, c1 in compute):
                continue
            report.add(
                "PTL203", Severity.WARNING,
                f"rank {rank}: collective {name!r} "
                f"({(t1 - t0) / 1e3:.2f} ms at ts={t0 / 1e3:.2f} ms) "
                f"overlaps no compute span — it serializes against "
                f"compute",
                hint="overlap the collective with compute (async "
                     "dispatch, gradient-bucket pipelining, 1F1B-style "
                     "interleaving); the straggler detector's "
                     "train.step_seconds spread confirms the exposed "
                     "latency at runtime")
    return report
