"""Static graph: Program capture + Executor replay.

Reference: the PIR static mode (SURVEY §3.3) — Python builds a
pir::Program under program_guard (ops append Operations instead of
executing), then Executor.run lowers and interprets it
(python/paddle/base/executor.py:1199, StandaloneExecutor at
fluid/framework/new_executor/standalone_executor.h:34).

TPU re-design: the "Program" records (primitive, inputs, attrs) triples as
ops execute on placeholder values (shape propagation via jax.eval_shape —
the InferMeta analog); Executor.run replays the instruction list as one
jax function and jit-compiles it per feed signature — the
pd_op_to_kernel_pass + PirInterpreter pipeline collapses into XLA.
"""
from __future__ import annotations

import functools
import hashlib
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import observability as _obs
from ..observability import opprof as _opprof
from ..core import dispatch
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

_obs_state = _obs.state

_M_RUNS = _obs.counter("executor.runs", "Executor.run invocations")
_M_COMPILES = _obs.counter(
    "executor.compiles",
    "fresh replay compiles (new program-fingerprint + feed signature)")
_M_REPLAYS = _obs.counter(
    "executor.replays", "Executor.run served from the compiled-replay cache")
_M_COMPILE_SECONDS = _obs.histogram(
    "executor.compile_seconds",
    "wall time of a replay compile (jax trace + XLA compile + first run)")
_M_INVALIDATIONS = _obs.counter(
    "executor.cache_invalidations",
    "program mutations (recorded op / grad section / rewrite pass) that "
    "changed the compiled-replay cache fingerprint")
_M_RECOMPILES_SAVED = _obs.counter(
    "executor.recompiles_saved",
    "cache hits on an entry compiled before the program's latest "
    "mutation — recompiles the old clear-on-any-change policy would "
    "have paid (e.g. a rewrite pass that turned out to be a no-op)")

_M_OPTIMIZED = _obs.counter(
    "executor.programs_optimized",
    "optimized-clone builds triggered by the Executor.run pre-compile "
    "hook (PADDLE_TPU_OPTIMIZE / FLAGS_optimize_programs)")

_M_OOM_CHECKS = _obs.counter(
    "executor.oom_checks",
    "pre-compile PTL301 memory-budget checks run on the compile-miss "
    "path (a known device budget + PADDLE_TPU_OOM_CHECK not off)")

#: compiled-replay entries kept per program; oldest evicted first
_REPLAY_CACHE_CAP = 64

#: optimized clones kept per program (keyed by fingerprint + fetch set)
_OPT_CLONE_CAP = 8

__all__ = ["Program", "program_guard", "data", "Executor",
           "default_main_program", "default_startup_program"]


class Program:
    """Recorded instruction list (the pir::Program analog)."""

    def __init__(self):
        self._placeholders: List[Tuple[str, int, tuple, Any]] = []
        self._consts: Dict[int, Any] = {}
        self._insts: List[Tuple[str, Tuple[int, ...], tuple, Tuple[int, ...]]] = []
        self._next_vid = 0
        self._vid_by_obj: Dict[int, int] = {}  # id(value object) -> vid
        self._keepalive: List[Any] = []  # pins captured objects: id() reuse
        self._feed_names: Dict[str, int] = {}
        self._cache: Dict[Any, Any] = {}
        self._mutations = 0
        self._consts_version = 0
        self._fingerprint: Optional[str] = None

    # -- recording -------------------------------------------------------
    def _new_vid(self) -> int:
        vid = self._next_vid
        self._next_vid += 1
        return vid

    def _vid_for_input(self, value) -> int:
        vid = self._vid_by_obj.get(id(value))
        if vid is not None:
            return vid
        if isinstance(value, jax.ShapeDtypeStruct):
            raise ValueError(
                "placeholder value used outside its source program"
            )
        # concrete constant created during capture (e.g. paddle.ones)
        vid = self._new_vid()
        self._consts[vid] = value
        self._vid_by_obj[id(value)] = vid
        self._keepalive.append(value)
        return vid

    def add_placeholder(self, name: str, shape, dtype):
        if name in self._feed_names:
            raise ValueError(f"duplicate static.data name {name!r}")
        # None (dynamic) dims captured as 1 for shape propagation; the real
        # extent binds at Executor.run from the feed arrays
        cap_shape = tuple(1 if s in (None, -1) else int(s) for s in shape)
        spec = jax.ShapeDtypeStruct(cap_shape, convert_dtype(dtype))
        vid = self._new_vid()
        self._vid_by_obj[id(spec)] = vid
        self._keepalive.append(spec)
        self._placeholders.append((name, vid, tuple(shape), dtype))
        self._feed_names[name] = vid
        return spec

    def record(self, prim_name: str, arrays, static) -> tuple:
        """Called from dispatch.call_primitive in capture mode."""
        in_vids = tuple(self._vid_for_input(a) for a in arrays)
        outs = dispatch.eval_shape(prim_name, arrays, static)
        outs = outs if isinstance(outs, tuple) else (outs,)
        out_vids = []
        for o in outs:
            vid = self._new_vid()
            self._vid_by_obj[id(o)] = vid
            self._keepalive.append(o)
            out_vids.append(vid)
        self._insts.append(
            (prim_name, in_vids, tuple(sorted(static.items(),
                                              key=lambda kv: kv[0])),
             tuple(out_vids))
        )
        self._invalidate()  # program changed; re-fingerprint compiled replays
        return outs

    def _invalidate(self):
        """Mark the program mutated. Compiled replays stay in ``_cache``
        keyed by the fingerprint of the state they were compiled against
        (Executor._compile snapshots that state), so a mutation that
        round-trips back to a previous structure — a no-op rewrite pass,
        or alternating pass pipelines — replays instead of recompiling."""
        self._fingerprint = None
        self._mutations += 1
        if _obs_state.on:
            _M_INVALIDATIONS.inc()

    def update_consts(self, mapping: Dict[int, Any]):
        """Rebind const VALUES under existing vids (parameter reload —
        deserialize_persistables / set_program_state). Bumps the consts
        version folded into the fingerprint, so compiled replays that
        baked the old values in can never be served again."""
        self._consts.update(mapping)
        self._consts_version += 1
        self._invalidate()

    def fingerprint(self) -> str:
        """Content hash of the program structure (instructions, feeds,
        const bindings + their reload version, recompute checkpoints) —
        the compiled-replay cache key component; recomputed lazily after
        mutations. Const values are versioned, not hashed: rebind them
        through :meth:`update_consts`, never by poking ``_consts``."""
        if self._fingerprint is None:
            h = hashlib.blake2b(digest_size=12)
            for name, vid, shape, dtype in self._placeholders:
                h.update(f"P|{name}|{vid}|{shape}|{dtype}".encode())
            h.update(repr((sorted(self._consts),
                           self._consts_version)).encode())
            for inst in self._insts:
                h.update(repr(inst).encode())
            h.update(repr(tuple(
                getattr(self, "_remat_checkpoints", ()) or ())).encode())
            self._fingerprint = h.hexdigest()
        return self._fingerprint

    def vid_of(self, t: Tensor) -> int:
        vid = self._vid_by_obj.get(id(t._value))
        if vid is None:
            raise ValueError(
                "fetch target was not produced by this Program"
            )
        return vid

    def record_gradients(self, loss_t: Tensor, wrt_ts: Sequence[Tensor]):
        """Append the grad section as ONE ``__gradients__`` instruction
        (reference: base/backward.py append_backward adds grad ops).

        The Executor replays it as ``jax.grad`` of a sub-replay of the
        forward instructions — so the backward is jax-generated, and the
        recompute pass (distributed/passes/program_passes.py) can mark
        checkpoint values that partition that sub-replay into
        ``jax.checkpoint`` segments."""
        loss_vid = self.vid_of(loss_t)
        wrt_vids = tuple(self._vid_for_input(t._value) for t in wrt_ts)
        fwd_len = len(self._insts)
        grad_vids = []
        outs = []
        for t in wrt_ts:
            # _value.dtype works for ShapeDtypeStruct placeholders AND
            # concrete arrays (np.asarray would make an object scalar of
            # a placeholder and force a device copy of an array)
            spec = jax.ShapeDtypeStruct(tuple(t.shape), t._value.dtype)
            vid = self._new_vid()
            self._vid_by_obj[id(spec)] = vid
            self._keepalive.append(spec)
            grad_vids.append(vid)
            outs.append(Tensor._from_value(spec, stop_gradient=True))
        self._insts.append(
            ("__gradients__", (loss_vid,) + wrt_vids,
             (("fwd_len", fwd_len),), tuple(grad_vids)))
        self._invalidate()
        return outs

    # -- parity surface --------------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test: bool = False) -> "Program":
        """Snapshot: ops recorded into the original AFTER the clone (e.g.
        the loss/optimizer section following a test-program clone) must not
        leak into the clone, so all mutable state is copied."""
        p = Program.__new__(Program)
        p._placeholders = list(self._placeholders)
        p._consts = dict(self._consts)
        p._insts = list(self._insts)
        p._next_vid = self._next_vid
        p._vid_by_obj = dict(self._vid_by_obj)
        p._keepalive = list(self._keepalive)
        p._feed_names = dict(self._feed_names)
        p._cache = {}
        p._mutations = 0
        p._consts_version = self._consts_version
        p._fingerprint = self._fingerprint
        if hasattr(self, "_remat_checkpoints"):
            p._remat_checkpoints = self._remat_checkpoints
        if hasattr(self, "_fetch_vids"):
            p._fetch_vids = self._fetch_vids
        if hasattr(self, "_pruned_feed_names"):
            p._pruned_feed_names = set(self._pruned_feed_names)
        return p

    @property
    def num_ops(self) -> int:
        return len(self._insts)

    def dump(self, annotate: bool = True) -> str:
        """Textual IR (reference: pir::Program::Print) — named vids with
        feed/const provenance, static attrs, and inferred result avals.
        Diagnostics from static.analysis cite ``op#N`` indices that read
        directly against this dump."""
        from .analysis.ir_dump import dump_program

        return dump_program(self, annotate=annotate)

    def __repr__(self):
        # annotate=False: repr must stay cheap (no eval_shape tracing) —
        # incidental reprs in logs/debuggers can hit huge programs
        return self.dump(annotate=False)


def _build_loss_fn(program: Program, fwd_len: int, loss_vid: int,
                   wrt_vids, env: Dict[int, Any]):
    """Build loss(wrt_values) as a sub-replay of the first ``fwd_len``
    instructions.

    When the recompute pass has marked checkpoint vids on the program
    (``_remat_checkpoints``), the forward is partitioned at their
    producing instructions and each segment runs under ``jax.checkpoint``
    — activations internal to a segment are rematerialized in the
    backward instead of saved, the reference auto_parallel_recompute
    semantics expressed the jax way."""
    insts = [i for i in program._insts[:fwd_len]
             if i[0] != "__gradients__"]
    ckpts = set(getattr(program, "_remat_checkpoints", ()) or ())
    wrt_vids = tuple(wrt_vids)

    # split after every instruction that produces a checkpoint vid
    segments: List[List[tuple]] = [[]]
    for inst in insts:
        segments[-1].append(inst)
        if ckpts and any(v in ckpts for v in inst[3]):
            segments.append([])
    segments = [s for s in segments if s]

    # dataflow interface per segment: traced inputs = values produced by
    # earlier segments or differentiated (wrt); outputs = values later
    # segments (or the loss) read. env consts/feeds are closed over.
    produced_before: set = set()
    seg_io = []
    all_produced = {v for inst in insts for v in inst[3]}
    for si, seg in enumerate(segments):
        seg_out = {v for inst in seg for v in inst[3]}
        ext_in = {v for inst in seg for v in inst[1]
                  if v not in seg_out and (v in produced_before
                                           or v in wrt_vids)}
        used_later = {v for later in segments[si + 1:]
                      for inst in later for v in inst[1]}
        used_later.add(loss_vid)
        seg_io.append((sorted(ext_in), sorted(seg_out & used_later)))
        produced_before |= seg_out

    def run_seg(seg, in_list, out_list, *in_vals):
        local = dict(zip(in_list, in_vals))

        def val(v):
            return local[v] if v in local else env[v]

        for prim_name, in_vids_, static_items, out_vids_ in seg:
            prim = dispatch.PRIMITIVES[prim_name]
            outs = prim.forward(*[val(v) for v in in_vids_],
                                **dict(static_items))
            outs = outs if isinstance(outs, tuple) else (outs,)
            for v, o in zip(out_vids_, outs):
                local[v] = o
        return tuple(local[v] for v in out_list)

    def loss_of(wrt_vals):
        flow: Dict[int, Any] = dict(zip(wrt_vids, wrt_vals))
        for seg, (in_list, out_list) in zip(segments, seg_io):
            fn = functools.partial(run_seg, seg, in_list, out_list)
            if ckpts and len(segments) > 1:
                fn = jax.checkpoint(fn)
            outs = fn(*[flow[v] for v in in_list])
            flow.update(dict(zip(out_list, outs)))
        return flow[loss_vid]

    return loss_of


def _replay_gradients(program: Program, fwd_len: int, loss_vid: int,
                      wrt_vids, env: Dict[int, Any]):
    loss_of = _build_loss_fn(program, fwd_len, loss_vid, wrt_vids, env)
    grads = jax.grad(loss_of)([env[v] for v in tuple(wrt_vids)])
    return tuple(grads)


class _ReplaySnapshot:
    """Frozen copy of exactly what Executor._compile's replay closure and
    _build_loss_fn read from a Program."""

    __slots__ = ("_insts", "_consts", "_feed_names", "_remat_checkpoints")

    def __init__(self, program: Program):
        self._insts = list(program._insts)
        self._consts = dict(program._consts)
        self._feed_names = dict(program._feed_names)
        self._remat_checkpoints = tuple(
            getattr(program, "_remat_checkpoints", ()) or ())


_default_main = Program()
_default_startup = Program()
_guard_stack: List[Program] = []


def default_main_program() -> Program:
    return _guard_stack[-1] if _guard_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """Reference: paddle.static.program_guard — ops inside the block are
    captured into `main_program` instead of executing."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _guard_stack.append(self.main)
        dispatch.set_capture_program(self.main)
        return self.main

    def __exit__(self, *exc):
        _guard_stack.pop()
        dispatch.set_capture_program(
            _guard_stack[-1] if _guard_stack else None
        )
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Reference: paddle.static.data — declare a feed placeholder."""
    prog = default_main_program()
    if not _guard_stack:
        raise RuntimeError(
            "static.data must be called under static.program_guard"
        )
    spec = prog.add_placeholder(name, shape, dtype)
    t = Tensor._from_value(spec, stop_gradient=True)
    t.name = name
    return t


def _optimize_enabled() -> bool:
    """The Executor pre-compile optimization gate: the
    ``PADDLE_TPU_OPTIMIZE`` env var wins, else ``FLAGS_optimize_programs``
    (core/flags.py)."""
    env = os.environ.get("PADDLE_TPU_OPTIMIZE")
    if env is not None:
        return env.lower() not in ("0", "", "false", "off")
    from ..core import flags

    return bool(flags.get_flag("optimize_programs"))


def _optimized_clone(program: Program, fetch_vids) -> Program:
    """Optimized clone of ``program`` for one (structure, fetch-set)
    pair, cached on the original program.

    The ORIGINAL program is never mutated: liveness-based rewrites are
    only valid for the fetch set they ran against, and the next run may
    fetch different values — so each fetch set optimizes its own clone
    (whose compiled replays live in the clone's own ``_cache``)."""
    from .analysis.rewrite import optimize_program

    cache = program.__dict__.setdefault("_opt_clones", {})
    key = (program.fingerprint(), tuple(fetch_vids))
    clone = cache.get(key)
    if clone is None:
        clone = program.clone()
        clone._fetch_vids = tuple(fetch_vids)
        optimize_program(clone, fetch=fetch_vids)
        cache[key] = clone
        while len(cache) > _OPT_CLONE_CAP:
            cache.pop(next(iter(cache)))
        if _obs_state.on:
            _M_OPTIMIZED.inc()
    else:
        # LRU refresh (same policy as the replay cache below): a steady
        # working set slightly over the cap must not re-optimize and
        # recompile every entry just before use
        cache.pop(key)
        cache[key] = clone
    return clone


def _oom_check_mode() -> str:
    """PTL301 pre-compile behavior: "warn" (default), "raise", "off"."""
    from .analysis.memory import OOM_CHECK_ENV

    mode = os.environ.get(OOM_CHECK_ENV, "warn").lower()
    return mode if mode in ("warn", "raise", "off") else "warn"


def _precompile_memory_check(program: Program, fetch_vids) -> None:
    """PTL301: predict peak memory BEFORE paying the compile.

    Runs on the compile-miss path only, and only when a device budget
    is known (``PADDLE_TPU_HBM_LIMIT_BYTES`` or the PJRT allocator's
    bytes_limit — 0 on CPU, so CI runs skip it for free). A predicted
    OOM is a loud ``warnings.warn`` carrying the rendered diagnostic
    (mode "raise" refuses instead): minutes of XLA compile time and a
    mid-compile device OOM are both worse than a false positive from a
    ~25%-accurate estimate.

    The replay Executor.run compiles here is single-device (feeds are
    host arrays; GSPMD sharding rides the dist.shard_tensor/jit paths,
    not this one), so the UNSHARDED estimate is the right comparison
    against the per-chip budget. A future sharded-executor path can
    attach its plan as ``program._placements`` (vid -> DistTensorSpec)
    and the estimate becomes per-chip automatically."""
    mode = _oom_check_mode()
    if mode == "off":
        return
    from .analysis.memory import device_memory_budget, lint_memory_budget

    limit = device_memory_budget()
    if limit <= 0:
        return
    if _obs_state.on:
        _M_OOM_CHECKS.inc()
    report = lint_memory_budget(program, fetch_vids, limit_bytes=limit,
                                placements=getattr(program, "_placements",
                                                   None),
                                name="executor")
    if not report.diagnostics:
        return
    if mode == "raise":
        from .analysis.diagnostics import ProgramVerificationError

        raise ProgramVerificationError(report,
                                       context="Executor.run pre-compile")
    import warnings

    warnings.warn(report.render("predicted OOM (PTL301) — compiling "
                                "anyway, set PADDLE_TPU_OOM_CHECK=raise "
                                "to refuse:"), stacklevel=3)


class Executor:
    """Reference: paddle.static.Executor (executor.py:1199) — replays the
    captured instruction list as one jitted XLA program per feed
    signature (the _ExecutorCache analog)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True,
            **kwargs):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        fetch_vids = tuple(
            program.vid_of(t) if isinstance(t, Tensor) else int(t)
            for t in fetch_list
        )
        if fetch_vids and _optimize_enabled():
            # swap in the lint->rewrite-optimized clone for this fetch
            # set; vids are stable across clone(), so fetch_vids and
            # feed names keep resolving
            program = _optimized_clone(program, fetch_vids)
        pruned = getattr(program, "_pruned_feed_names", ()) or ()
        if pruned:
            # feeds the optimizer pruned stay ACCEPTED (and ignored):
            # pruning relaxes the feed contract, it must not break
            # callers still passing the old dict
            feed = {k: v for k, v in feed.items() if k not in pruned}
        feed_items = sorted(feed.items())
        feed_names = tuple(k for k, _ in feed_items)
        declared = {n for n, _, _, _ in program._placeholders}
        missing = declared - set(feed_names)
        if missing:
            raise ValueError(f"missing feeds: {sorted(missing)}")
        unknown = set(feed_names) - declared
        if unknown:
            raise ValueError(
                f"unknown feed names {sorted(unknown)}: the program "
                f"declares placeholders {sorted(declared) or '(none)'}")
        arrays = [np.asarray(v._value if isinstance(v, Tensor) else v)
                  for _, v in feed_items]
        if _obs_state.on:
            _M_RUNS.inc()
        prof = _opprof.active_session()
        if prof is not None:
            # op-level profiling (PADDLE_TPU_OPPROF): the pacer decides
            # whether THIS run pays for the eager per-op-timed replay;
            # when it declines (None) we fall through to the jit path
            prof_outs = prof.maybe_profiled_run(program, feed_names,
                                                arrays, fetch_vids)
            if prof_outs is not None:
                if return_numpy:
                    return [np.asarray(o) for o in prof_outs]
                return [Tensor._from_value(o) for o in prof_outs]
        feed_sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        # keyed by program CONTENT, not clear-on-change: switching between
        # programs, or a rewrite pipeline that lands back on a structure
        # already compiled, replays instead of recompiling
        key = (program.fingerprint(), feed_names, feed_sig, fetch_vids)
        entry = program._cache.get(key)
        if entry is None:
            # predicted-OOM check rides the compile-miss path: the
            # estimate costs ms, the compile it can veto costs minutes
            _precompile_memory_check(program, fetch_vids)
            with _obs.span("Executor.compile",
                           histogram=_M_COMPILE_SECONDS) as sp:
                fn = self._compile(program, feed_names, fetch_vids)
                outs = fn(*arrays)  # first call: jax trace + XLA compile
            program._cache[key] = (fn, program._mutations)
            while len(program._cache) > _REPLAY_CACHE_CAP:
                program._cache.pop(next(iter(program._cache)))
            if _obs_state.on:
                _M_COMPILES.inc()
                _obs.emit(
                    "executor.compile", fingerprint=key[0],
                    feed=[f"{n}:{list(s)}:{d}"
                          for n, (s, d) in zip(feed_names, feed_sig)],
                    num_ops=program.num_ops, num_fetch=len(fetch_vids),
                    seconds=sp.seconds)
        else:
            # LRU refresh: eviction pops from the front, so a hit moves
            # its entry to the back (a steady working set slightly over
            # the cap would otherwise evict every entry just before use)
            program._cache.pop(key)
            program._cache[key] = entry
            fn, born = entry
            if _obs_state.on:
                _M_REPLAYS.inc()
                if born < program._mutations:
                    _M_RECOMPILES_SAVED.inc()
            outs = fn(*arrays)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor._from_value(o) for o in outs]

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Drive a captured program from a slot dataset (reference:
        base/executor.py:3222 — trainer threads consuming the C++
        DataFeed; here the threaded batch_iterator feeds Executor.run).

        dataset must carry a data feed: set one with
        ``dataset.set_data_feed(MultiSlotDataFeed(slots))``; its slot
        names must match the program's placeholder feed names."""
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler, train=True)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler, train=False)

    def _run_from_dataset(self, program, dataset, fetch_list, fetch_info,
                          print_period, fetch_handler, train):
        from ..distributed.ps.dataset import batch_iterator

        if dataset is None:
            raise ValueError("dataset is required")
        feed = getattr(dataset, "_data_feed", None)
        if feed is None:
            raise ValueError(
                "dataset has no data feed: call "
                "dataset.set_data_feed(MultiSlotDataFeed(slots)) first")
        results = []
        for step, batch in enumerate(batch_iterator(dataset, feed)):
            outs = self.run(program, feed=batch, fetch_list=fetch_list)
            if fetch_list:
                results.append(outs)
                if fetch_handler is not None:
                    fetch_handler(step, outs)
                if print_period and step % print_period == 0 and outs:
                    names = fetch_info or [f"fetch{i}"
                                           for i in range(len(outs))]
                    summary = ", ".join(
                        f"{n}={np.asarray(o).ravel()[:1]}"
                        for n, o in zip(names, outs))
                    print(f"step {step}: {summary}")
        return results

    @staticmethod
    def _compile(program: Program, feed_names, fetch_vids,
                 donate: bool = False):
        # snapshot: the replay closure reads the program at call time, and
        # cache entries now outlive mutations (fingerprint keying), so the
        # compiled executable must close over the structure it was
        # compiled against, not whatever the program becomes later. Only
        # the four fields replay()/_build_loss_fn() read are copied — a
        # full clone() would pin _keepalive/_vid_by_obj per cache entry.
        program = _ReplaySnapshot(program)
        name_to_vid = program._feed_names

        def replay(*feed_arrays):
            env: Dict[int, Any] = dict(program._consts)
            for n, a in zip(feed_names, feed_arrays):
                env[name_to_vid[n]] = a
            for idx, (prim_name, in_vids, static_items,
                      out_vids) in enumerate(program._insts):
                if prim_name == "__gradients__":
                    # the forward is whatever precedes this instruction
                    # NOW — rewrite passes may have shrunk the list, so
                    # the captured fwd_len count cannot be trusted
                    grads = _replay_gradients(
                        program, idx, in_vids[0], in_vids[1:], env)
                    for v, g in zip(out_vids, grads):
                        env[v] = g
                    continue
                prim = dispatch.PRIMITIVES[prim_name]
                outs = prim.forward(
                    *[env[v] for v in in_vids], **dict(static_items)
                )
                outs = outs if isinstance(outs, tuple) else (outs,)
                for v, o in zip(out_vids, outs):
                    env[v] = o
            return [env[v] for v in fetch_vids]

        if donate:
            # inference memory_optim: feed buffers are donated so XLA's
            # buffer assignment reuses them for outputs/temps
            return jax.jit(replay,
                           donate_argnums=tuple(range(len(feed_names))))
        return jax.jit(replay)

    def close(self):
        pass
