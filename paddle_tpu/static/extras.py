"""paddle.static surface completion.

Reference: python/paddle/static/__init__.py — gradient utilities
(append_backward, gradients from base/backward.py), scopes
(global_scope/scope_guard), program serialization (static/io.py: save/load,
serialize_*/deserialize_*, normalize_program, program state), places,
Print/py_func, ExponentialMovingAverage (incubate/optimizer), accuracy/auc
(static/nn/metric.py), device_guard, BuildStrategy/CompiledProgram, IPU
stubs.
"""
from __future__ import annotations

import contextlib
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter, apply
from ..ops._helpers import defprim, ensure_tensor

__all__ = [
    "append_backward", "gradients", "Scope", "global_scope", "scope_guard",
    "BuildStrategy", "CompiledProgram", "Print", "py_func",
    "WeightNormParamAttr", "ExponentialMovingAverage", "save", "load",
    "serialize_program", "serialize_persistables", "save_to_file",
    "deserialize_program", "deserialize_persistables", "load_from_file",
    "normalize_program", "load_program_state", "set_program_state",
    "cpu_places", "cuda_places", "xpu_places", "Variable",
    "create_global_var", "accuracy", "auc", "device_guard",
    "ipu_shard_guard", "set_ipu_shard", "IpuCompiledProgram", "IpuStrategy",
    "ctr_metric_bundle",
]

Variable = Tensor  # static Variable == eager Tensor in the collapsed design


# ---------------------------------------------------------------------------
# gradients
# ---------------------------------------------------------------------------
def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference: base/backward.py append_backward — adds the grad section
    and returns [(param, grad)]. In the collapsed design the tape IS the
    program, so this runs backward and pairs params with their grads.

    Under static capture (program_guard) the grad section is recorded
    into the Program instead (see gradients)."""
    loss = ensure_tensor(loss)
    if _capture_grad_possible(loss):
        if parameter_list is None:
            raise ValueError(
                "append_backward under program_guard needs an explicit "
                "parameter_list (the eager tape is off during capture)")
        grads = gradients([loss], list(parameter_list))
        return list(zip(parameter_list, grads))
    loss.backward(retain_graph=True)
    params = parameter_list
    if params is None:
        from ..nn.layer import Layer  # noqa: F401 — for type context

        # all Parameters reachable on the tape: collect from grad results
        params = [
            p for p in _walk_tape_params(loss)
        ]
    out = []
    for p in params:
        g = p.grad if hasattr(p, "grad") else None
        out.append((p, g))
    return out


def _walk_tape_params(loss):
    """Collect Parameter leaves contributing to loss via the grad graph."""
    seen = set()
    out = []
    stack = [getattr(loss, "_node", None)]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        for t in (getattr(node, "saved_tensors", None) or []):
            if isinstance(t, Parameter) and id(t) not in seen:
                seen.add(id(t))
                out.append(t)
        for edge in (getattr(node, "in_edges", None) or []):
            if edge is not None:
                prod = edge[0]
                stack.append(prod if hasattr(prod, "in_edges") else None)
    return out


def _capture_grad_possible(loss) -> bool:
    import jax

    from ..core import dispatch

    return dispatch.capture_active() and isinstance(
        loss._value, jax.ShapeDtypeStruct)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference: paddle.static.gradients — grads of targets w.r.t inputs.

    Under static capture the eager tape is off, so this records a
    ``__gradients__`` instruction into the Program (the append_backward
    grad-section analog); the Executor replays it as jax.grad over the
    captured forward — which is what lets the recompute pass turn
    checkpoint marks into jax.checkpoint segments."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    loss = ensure_tensor(targets[0])
    if _capture_grad_possible(loss):
        from ..core import dispatch

        if target_gradients is not None:
            raise NotImplementedError(
                "target_gradients is not supported under static capture")
        if no_grad_set:
            raise NotImplementedError(
                "no_grad_set is not supported under static capture")
        # multiple targets: paddle semantics differentiate their sum —
        # the adds are captured as ordinary instructions
        for extra in targets[1:]:
            loss = loss + ensure_tensor(extra)
        prog = dispatch._capture_program
        return prog.record_gradients(loss, [ensure_tensor(i)
                                            for i in inputs])
    from ..autograd import grad as _grad

    outs = _grad(targets, inputs, target_gradients, retain_graph=True,
                 allow_unused=True)
    return outs if isinstance(outs, (list, tuple)) else [outs]


# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------
class Scope:
    """Name -> variable store (reference: core Scope)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar())

    def find_var(self, name):
        return self._vars.get(name)

    def new_scope(self):
        return Scope()


class _ScopeVar:
    def __init__(self):
        self._tensor = None

    def get_tensor(self):
        return self._tensor

    def set(self, value, place=None):
        self._tensor = ensure_tensor(value)


_global_scope = Scope()
_scope_stack = []


def global_scope():
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ---------------------------------------------------------------------------
# compiled-program façade
# ---------------------------------------------------------------------------
class BuildStrategy:
    """Knob bag (reference: pybind BuildStrategy). XLA performs the fusion/
    memory-opt roles; flags recorded for API parity."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.build_cinn_pass = False


class CompiledProgram:
    """Reference: static/compiler.py CompiledProgram — the Executor accepts
    it anywhere a Program is accepted; compilation is the Executor's jit
    cache, so this wrapper just carries the strategy."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


# ---------------------------------------------------------------------------
# Print / py_func
# ---------------------------------------------------------------------------
def _print_fwd(x, *, message, first_n, summarize):
    jax.debug.print(message + " {}", x)
    return x


defprim("static_print_p", _print_fwd, jittable=False)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Reference: static/nn/control_flow.py Print — identity op that prints
    at execution (jax.debug.print inside the compiled program)."""
    return apply("static_print_p", ensure_tensor(input),
                 message=message or "", first_n=int(first_n),
                 summarize=int(summarize))


_py_func_registry = {}  # (func, shapes-sig) -> prim name; holds func refs
_py_func_counter = [0]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: static/nn/common.py py_func — host-callback op.
    Implemented over jax.pure_callback so it survives jit. Registration is
    keyed by (function object, output signature): new output shapes get a
    fresh primitive, and the strong func reference prevents id() reuse
    after garbage collection."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    arrays = [ensure_tensor(t) for t in xs]
    outs_spec = out if isinstance(out, (list, tuple)) else [out]
    shapes = tuple(jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype)
                   for o in outs_spec)
    sig = tuple((s.shape, str(s.dtype)) for s in shapes)
    # bound methods produce a fresh object per attribute access — key on the
    # underlying function so they don't leak one primitive per call
    key = (getattr(func, "__func__", func), sig)
    if len(_py_func_registry) > 512:
        # bound: per-call lambdas would otherwise grow the primitive table
        # without limit
        from ..core import dispatch as _dispatch

        for (_, _), old_name in list(_py_func_registry.items())[:256]:
            _dispatch.PRIMITIVES.pop(old_name, None)
        for k in list(_py_func_registry)[:256]:
            del _py_func_registry[k]
    name = _py_func_registry.get(key)
    if name is None:
        _py_func_counter[0] += 1
        name = f"py_func_{_py_func_counter[0]}_p"
        _py_func_registry[key] = name

        def host_fn(*vals):
            res = func(*[np.asarray(v) for v in vals])
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r, dtype=s.dtype)
                         for r, s in zip(res, shapes))

        defprim(name, lambda *arrs: jax.pure_callback(
            host_fn, shapes, *arrs), multi_out=len(shapes) > 1,
            jittable=False)
    return apply(name, *arrays)


class WeightNormParamAttr:
    """Reference: static/nn/common.py WeightNormParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..nn.param_attr import ParamAttr

        self.dim = dim
        self.attr = ParamAttr(name=name, initializer=initializer,
                              learning_rate=learning_rate,
                              regularizer=regularizer, trainable=trainable)


class ExponentialMovingAverage:
    """EMA of parameters with apply()/restore()
    (reference: static/__init__.py ExponentialMovingAverage)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = {}
        self._params = []
        self._step = 0

    def update(self, parameters=None):
        if parameters is not None:
            for p in parameters:
                if id(p) not in {id(q) for q in self._params}:
                    self._params.append(p)
        self._step += 1
        for p in self._params:
            prev = self._ema.get(id(p))
            v = p._value.astype(jnp.float32)
            self._ema[id(p)] = (v if prev is None
                                else self._decay * prev + (1 - self._decay) * v)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._value
            ema = self._ema.get(id(p))
            if ema is not None:
                # bias-corrected like the reference
                corr = ema / (1.0 - self._decay ** self._step)
                p._replace_value(corr.astype(p._value.dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            b = self._backup.pop(id(p), None)
            if b is not None:
                p._replace_value(b)


# ---------------------------------------------------------------------------
# program serialization (static/io.py)
# ---------------------------------------------------------------------------
def _collect_state(program):
    """Persistable state attached to a Program (params created under its
    guard are tracked in _consts)."""
    state = {}
    for vid, v in getattr(program, "_consts", {}).items():
        if hasattr(v, "shape"):
            state[f"var_{vid}"] = np.asarray(v)
    return state


def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      **kwargs):
    from .program import default_main_program

    program = program or default_main_program()
    fetch_vids = list(getattr(program, "_fetch_vids", ()))
    if fetch_vars:
        fetch_vids = [program.vid_of(v) for v in fetch_vars]
    return pickle.dumps({
        "placeholders": program._placeholders,
        "insts": program._insts,
        "next_vid": program._next_vid,
        "feed_names": program._feed_names,
        "fetch_vids": fetch_vids,
    })


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kwargs):
    from .program import default_main_program

    program = program or default_main_program()
    return pickle.dumps(_collect_state(program))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    return program_from_payload(pickle.loads(data))


def load_static_artifact(path_prefix, params_file=None):
    """Load <prefix>.pdmodel (+ .pdparams) when it holds a STATIC
    program payload; returns the Program or None for other artifact
    kinds (e.g. jit.save StableHLO payloads). The single loader behind
    both static.load_inference_model and inference.Predictor."""
    p = path_prefix if path_prefix.endswith(".pdmodel") \
        else path_prefix + ".pdmodel"
    try:
        payload = pickle.loads(load_from_file(p))
    except Exception:
        # not a pickled static program (missing file, foreign bytes like
        # a protobuf .pdmodel, or a stream referencing renamed classes):
        # let the caller fall back to the StableHLO/jit loader, whose
        # error message names the actual artifact kind
        return None
    if not (isinstance(payload, dict) and "insts" in payload):
        return None
    prog = program_from_payload(payload)
    pp = params_file or p[: -len(".pdmodel")] + ".pdparams"
    try:
        deserialize_persistables(prog, load_from_file(pp))
    except FileNotFoundError:
        pass
    return prog


def program_from_payload(payload):
    """Rebuild a Program from an already-unpickled .pdmodel payload."""
    from .program import Program

    p = Program()
    p._placeholders = payload["placeholders"]
    p._insts = payload["insts"]
    p._next_vid = payload["next_vid"]
    p._feed_names = payload["feed_names"]
    p._fetch_vids = tuple(payload.get("fetch_vids", ()))
    return p


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    # update_consts (not a bare dict update): bumps the consts version in
    # the replay-cache fingerprint, so executables that baked the old
    # weight values in are never served again
    program.update_consts({
        int(k.split("_", 1)[1]): jnp.asarray(v) for k, v in state.items()
    })
    return program


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: static/io.py normalize_program — prune to the
    feed->fetch slice (dead-code elimination) and pin the fetch targets
    so save/Predictor know the program's outputs."""
    from ..distributed.passes import new_pass

    clone = program.clone(for_test=True)
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    fetch_vids = [program.vid_of(v) for v in fetch_vars]
    new_pass("dead_code_elimination",
             {"fetch": fetch_vids}).apply(clone, None)
    # prune placeholders too: keep the declared feeds plus anything the
    # surviving instructions still read — stray feeds would otherwise
    # reappear as required Predictor inputs
    feed_vids = set()
    for v in (feed_vars if isinstance(feed_vars, (list, tuple))
              else [feed_vars]):
        if v is not None:
            feed_vids.add(program.vid_of(v) if not isinstance(v, int)
                          else v)
    used = {v for inst in clone._insts for v in inst[1]}
    clone._placeholders = [
        ph for ph in clone._placeholders
        if ph[1] in feed_vids or ph[1] in used]
    clone._feed_names = {name: vid for name, vid, _s, _d
                         in clone._placeholders}
    clone._fetch_vids = tuple(fetch_vids)
    return clone


def save(program, model_path, protocol=4, **configs):
    """Reference: static/io.py save — <path>.pdmodel + .pdparams."""
    save_to_file(model_path + ".pdmodel", serialize_program(program=program))
    save_to_file(model_path + ".pdparams",
                 serialize_persistables(program=program))


def load(program, model_path, executor=None, var_list=None):
    data = load_from_file(model_path + ".pdparams")
    deserialize_persistables(program, data, executor)


def load_program_state(model_path, var_list=None):
    return pickle.loads(load_from_file(model_path + ".pdparams"))


def set_program_state(program, state_dict):
    program.update_consts({
        int(k.split("_", 1)[1]): jnp.asarray(v)
        for k, v in state_dict.items() if k.startswith("var_")
    })  # versioned rebind — same reason as deserialize_persistables


# ---------------------------------------------------------------------------
# places / vars / metrics / guards
# ---------------------------------------------------------------------------
def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """On TPU builds, accelerator places enumerate TPU chips."""
    from ..core.place import TPUPlace

    if device_ids is None:
        device_ids = range(len([d for d in jax.devices()
                                if d.platform != "cpu"]) or 1)
    return [TPUPlace(i) for i in device_ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Reference: tensor/creation.py create_global_var."""
    from ..core.dtype import convert_dtype

    t = Parameter(jnp.full(tuple(shape), value, convert_dtype(dtype)),
                  trainable=not persistable, name=name)
    t.persistable = persistable
    return t


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Reference: static/nn/metric.py accuracy — top-k accuracy."""
    x = ensure_tensor(input)._value
    lab = ensure_tensor(label)._value.reshape(-1)
    topk = jnp.argsort(-x, axis=-1)[:, :k]
    hit = jnp.any(topk == lab[:, None], axis=-1)
    return Tensor._from_value(jnp.mean(hit.astype(jnp.float32)))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Reference: static/nn/metric.py auc — exact ROC-AUC over the batch
    (threshold bucketing is a CUDA artifact; sort-based here)."""
    x = ensure_tensor(input)._value
    scores = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else x.reshape(-1)
    lab = ensure_tensor(label)._value.reshape(-1).astype(jnp.float32)
    order = jnp.argsort(scores)
    lab_sorted = lab[order]
    n_pos = jnp.sum(lab_sorted)
    n_neg = lab_sorted.shape[0] - n_pos
    ranks = jnp.arange(1, lab_sorted.shape[0] + 1, dtype=jnp.float32)
    sum_pos_ranks = jnp.sum(ranks * lab_sorted)
    auc_v = (sum_pos_ranks - n_pos * (n_pos + 1) / 2) / jnp.maximum(
        n_pos * n_neg, 1.0)
    return (Tensor._from_value(auc_v),)


@contextlib.contextmanager
def device_guard(device=None):
    """Reference: static/device_guard — op placement hint. XLA handles
    placement; the guard records the request for parity."""
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU support is not part of the TPU build")
    yield  # pragma: no cover


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU support is not part of the TPU build")


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU support is not part of the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is not part of the TPU build")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """Reference: static/nn/metric.py ctr_metric_bundle — returns
    (auc, batch_auc-like stats) for CTR models; reduced surface."""
    return auc(input, label)
