"""Static control-flow ops: cond / while_loop / case / switch_case.

Reference: python/paddle/static/nn/control_flow.py (cond at :1485,
while_loop at :682, case at :937, switch_case at :1060) and
static_pylayer.py. The reference builds sub-block programs executed by
the interpreter; the TPU-first mapping is:

- **Eager** (predicate concrete): plain Python control flow runs exactly
  one branch — the reference dygraph behavior — and the executed branch
  records onto the autograd tape, so gradients work naturally (including
  through a Python ``while``, which unrolls on the tape).
- **Traced** (predicate is a jax tracer, i.e. inside ``jit.to_static``):
  ``cond``/``case``/``switch_case`` trace *both* branches and combine
  outputs with a select — speculative execution of short branches is the
  idiomatic XLA/TPU lowering for data-dependent branching (keeps shapes
  static, stays differentiable, lets the compiler fuse both sides).
  ``while_loop`` lowers to ``lax.while_loop`` (forward-only under trace:
  reverse-mode through an unbounded loop is not defined; train loops
  needing gradients through a while fall back to eager via
  ``to_static``'s fallback path).
"""
from __future__ import annotations

import functools

import jax

from ...ops._helpers import ensure_tensor
from ...core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "static_pylayer"]


def _is_traced(value) -> bool:
    return isinstance(value, jax.core.Tracer)


def _select_nest(pred, t_out, f_out):
    """Leaf-wise select between two same-structure branch outputs."""
    from ...ops.manipulation import where

    t_leaves, t_tree = jax.tree_util.tree_flatten(
        t_out, is_leaf=lambda x: isinstance(x, Tensor))
    f_leaves, f_tree = jax.tree_util.tree_flatten(
        f_out, is_leaf=lambda x: isinstance(x, Tensor))
    if t_tree != f_tree or len(t_leaves) != len(f_leaves):
        raise ValueError(
            "true_fn and false_fn must return the same structure of "
            f"outputs, got {t_tree} vs {f_tree}")
    merged = []
    for a, b in zip(t_leaves, f_leaves):
        if isinstance(a, Tensor) or isinstance(b, Tensor):
            a, b = ensure_tensor(a), ensure_tensor(b)
            if a.shape != b.shape:
                raise ValueError(
                    "branch outputs must have matching shapes under a "
                    f"traced predicate, got {a.shape} vs {b.shape}")
            merged.append(where(pred, a, b))
        else:
            if a != b:
                raise ValueError(
                    "non-Tensor branch outputs must be equal under a "
                    f"traced predicate, got {a!r} vs {b!r}")
            merged.append(a)
    return jax.tree_util.tree_unflatten(t_tree, merged)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Reference: static/nn/control_flow.py:1485. Both callables take no
    arguments (capture by closure) and must return the same structure.
    """
    if not callable(true_fn):
        raise TypeError("The true_fn in cond must be callable.")
    if not callable(false_fn):
        raise TypeError("The false_fn in cond must be callable.")
    pred = ensure_tensor(pred)
    if not _is_traced(pred._value):
        return true_fn() if bool(pred._value) else false_fn()
    return _select_nest(pred, true_fn(), false_fn())


def _normalize_vars(out, n_expected, what):
    if isinstance(out, (list, tuple)):
        out = list(out)
    else:
        out = [out]
    if len(out) != n_expected:
        raise ValueError(
            f"{what} must return the same number of loop_vars "
            f"({n_expected}), got {len(out)}")
    return out


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Repeat ``body`` while ``cond`` holds.

    Reference: static/nn/control_flow.py:682. ``cond(*loop_vars)`` returns
    a scalar bool Tensor; ``body(*loop_vars)`` returns updated loop_vars
    (same structure, shapes and dtypes). Returns the final loop_vars as a
    list.
    """
    if not callable(cond):
        raise TypeError("The cond in while_loop must be callable.")
    if not callable(body):
        raise TypeError("The body in while_loop must be callable.")
    if not isinstance(loop_vars, (list, tuple)) or len(loop_vars) == 0:
        raise ValueError("loop_vars must be a non-empty list/tuple.")
    loop_vars = list(loop_vars)
    n = len(loop_vars)

    first = ensure_tensor(cond(*loop_vars))
    if not _is_traced(first._value):
        # eager: Python loop; every executed op lands on the autograd
        # tape, so this path is differentiable
        keep_going = bool(first._value)
        while keep_going:
            loop_vars = _normalize_vars(body(*loop_vars), n, "body")
            keep_going = bool(ensure_tensor(cond(*loop_vars))._value)
        return loop_vars

    # traced: lower to lax.while_loop on the raw values. User callables
    # see Tensor-wrapped tracers; recording is paused so inner-scope
    # tracers never leak onto the tape (forward-only under trace).
    from ...autograd import engine as _engine

    flat0 = []
    treedefs = []
    for v in loop_vars:
        leaves, tree = jax.tree_util.tree_flatten(
            v, is_leaf=lambda x: isinstance(x, Tensor))
        flat0.append([ensure_tensor(l)._value for l in leaves])
        treedefs.append(tree)

    def wrap(flat):
        vars_ = []
        for leaves, tree in zip(flat, treedefs):
            vars_.append(jax.tree_util.tree_unflatten(
                tree, [Tensor._from_value(l) for l in leaves]))
        return vars_

    def unwrap(vars_):
        flat = []
        for v, tree in zip(vars_, treedefs):
            leaves, t2 = jax.tree_util.tree_flatten(
                v, is_leaf=lambda x: isinstance(x, Tensor))
            if t2 != tree:
                raise ValueError(
                    "body must preserve the structure of loop_vars")
            flat.append([ensure_tensor(l)._value for l in leaves])
        return flat

    def cond_raw(flat):
        with _engine.no_grad():
            r = ensure_tensor(cond(*wrap(flat)))
        return r._value.reshape(())

    def body_raw(flat):
        with _engine.no_grad():
            out = _normalize_vars(body(*wrap(flat)), n, "body")
        return unwrap(out)

    final = jax.lax.while_loop(cond_raw, body_raw, flat0)
    return wrap(final)


def case(pred_fn_pairs, default=None, name=None):
    """if/elif/.../else chain: run the fn of the first true pred.

    Reference: static/nn/control_flow.py:937. With ``default=None`` the
    last pair's fn serves as the default.
    """
    if not isinstance(pred_fn_pairs, (list, tuple)):
        raise TypeError("pred_fn_pairs must be a list or tuple.")
    for pair in pred_fn_pairs:
        if not isinstance(pair, tuple) or len(pair) != 2:
            raise TypeError(
                "Each element of pred_fn_pairs must be a (pred, fn) tuple.")
        if not callable(pair[1]):
            raise TypeError("The fn of each pred_fn_pair must be callable.")
    if default is None:
        default = pred_fn_pairs[-1][1]
        pred_fn_pairs = pred_fn_pairs[:-1]
    elif not callable(default):
        raise TypeError("The default in case must be callable.")

    false_fn = default
    for pred, true_fn in reversed(list(pred_fn_pairs)):
        false_fn = functools.partial(
            cond, pred, true_fn=true_fn, false_fn=false_fn)
    return false_fn()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """C-style switch on an integer scalar Tensor.

    Reference: static/nn/control_flow.py:1060. ``branch_fns`` is a dict
    {int: fn}, a list of (int, fn) pairs, or a list of fns (indexed by
    position). With ``default=None`` the fn with the max index is the
    default.
    """
    from ...ops.comparison import equal

    branch_index = ensure_tensor(branch_index)
    if isinstance(branch_fns, dict):
        pairs = list(branch_fns.items())
    elif isinstance(branch_fns, (list, tuple)):
        if branch_fns and not isinstance(branch_fns[0], tuple):
            pairs = list(enumerate(branch_fns))
        else:
            pairs = list(branch_fns)
    else:
        raise TypeError("branch_fns must be a dict, list or tuple.")
    keys = []
    for key, fn in pairs:
        if not isinstance(key, int):
            raise TypeError("The key of branch_fns must be an integer.")
        if key in keys:
            raise ValueError(
                f"The key in branch_fns must be unique, but '{key}' "
                "appears more than once.")
        keys.append(key)
        if not callable(fn):
            raise TypeError(f"The fn for key {key} must be callable.")
    if default is None:
        pairs = sorted(pairs)
        default = pairs[-1][1]
        pairs = pairs[:-1]
    elif not callable(default):
        raise TypeError("The default in switch_case must be callable.")

    false_fn = default
    for key, fn in pairs:
        pred = equal(branch_index,
                     ensure_tensor(key, dtype=branch_index.dtype))
        false_fn = functools.partial(cond, pred, true_fn=fn,
                                     false_fn=false_fn)
    return false_fn()


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Run ``forward_fn(*inputs)`` with a custom backward.

    Reference: static/nn/static_pylayer.py. Delegates to the eager
    PyLayer mechanism (the single execution path of this framework):
    ``backward_fn`` receives output grads and returns input grads.
    """
    from ...autograd.py_layer import PyLayer

    if not callable(forward_fn):
        raise TypeError("forward_fn must be callable.")
    if backward_fn is None:
        from ...autograd import engine as _engine

        with _engine.no_grad():
            return forward_fn(*inputs)

    class _StaticPyLayer(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            return backward_fn(*grads)

    return _StaticPyLayer.apply(*inputs)
