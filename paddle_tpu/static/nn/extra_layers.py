"""Remaining static.nn layer functions.

Reference: python/paddle/static/nn/common.py (conv2d_transpose :~,
conv3d, data_norm, deform_conv2d, instance_norm, bilinear_tensor_product,
row_conv, spectral_norm) and loss.py (nce). Each builds the matching
dynamic layer (or op) and applies it — the static-capture machinery
records the ops like any other call.
"""
from __future__ import annotations

import numpy as np

from ... import nn as _nn
from ...framework.misc import create_parameter
from ...ops._helpers import defprim as _defprim, ensure_tensor
from .common import _maybe_act

__all__ = [
    "conv2d_transpose", "conv3d", "conv3d_transpose", "instance_norm",
    "data_norm", "deform_conv2d", "bilinear_tensor_product", "row_conv",
    "spectral_norm", "nce",
]


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    x = ensure_tensor(input)
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    if filter_size is None:
        raise ValueError("filter_size is required in the TPU build "
                         "(no output_size-driven inference)")
    layer = _nn.Conv2DTranspose(
        in_channels, num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr, data_format=data_format)
    out = layer(x, output_size=output_size)
    return _maybe_act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    x = ensure_tensor(input)
    in_channels = x.shape[1] if data_format == "NCDHW" else x.shape[-1]
    layer = _nn.Conv3D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _maybe_act(layer(x), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    x = ensure_tensor(input)
    in_channels = x.shape[1] if data_format == "NCDHW" else x.shape[-1]
    if filter_size is None:
        raise ValueError("filter_size is required in the TPU build")
    layer = _nn.Conv3DTranspose(
        in_channels, num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr, data_format=data_format)
    out = layer(x, output_size=output_size)
    return _maybe_act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    x = ensure_tensor(input)
    cls = {3: _nn.InstanceNorm1D, 4: _nn.InstanceNorm2D,
           5: _nn.InstanceNorm3D}.get(len(x.shape))
    if cls is None:
        raise ValueError(f"instance_norm expects 3-5D input, got {x.shape}")
    layer = cls(x.shape[1], epsilon=epsilon, weight_attr=param_attr,
                bias_attr=bias_attr)
    return layer(x)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Normalization by accumulated batch statistics (reference data_norm:
    x_norm = (x - mean) / sqrt(scale), stats kept as size/sum/square-sum
    accumulators updated outside the gradient)."""
    from ...ops import math as m

    x = ensure_tensor(input)
    d = x.shape[-1] if data_layout == "NHWC" or len(x.shape) == 2 \
        else x.shape[1]
    dt = "float32"
    # stat accumulators are NOT trainable and never take the user's
    # param_attr (whose initializer would corrupt them): they are updated
    # in place from each batch, outside the gradient — the reference
    # kernel's size/sum/square-sum summary update
    batch_size = create_parameter(
        [d], dt, default_initializer=_nn.initializer.Constant(1e4))
    batch_sum = create_parameter(
        [d], dt, default_initializer=_nn.initializer.Constant(0.0))
    batch_square_sum = create_parameter(
        [d], dt, default_initializer=_nn.initializer.Constant(1e4))
    for stat in (batch_size, batch_sum, batch_square_sum):
        stat.stop_gradient = True
    mean = m.divide(batch_sum, batch_size)
    scale = m.rsqrt(m.add(m.divide(batch_square_sum, batch_size),
                          ensure_tensor(float(epsilon))))
    out = m.multiply(m.subtract(x, mean), scale)
    # The reference updates the accumulators per step through optimizer-
    # injected summary ops; this functional form mints fresh stats per
    # call, so per-call accumulation would be unobservable. Stat-driven
    # normalization with persistent accumulators belongs to a Layer that
    # owns the stats (load pretrained values into these parameters).
    if enable_scale_and_shift:
        w = create_parameter(
            [d], dt, attr=param_attr,
            default_initializer=_nn.initializer.Constant(1.0))
        b = create_parameter(
            [d], dt, attr=param_attr,
            default_initializer=_nn.initializer.Constant(0.0))
        out = m.add(m.multiply(out, w), b)
    return _maybe_act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  modulated=True, name=None):
    x = ensure_tensor(input)
    k = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w = create_parameter(
        [num_filters, x.shape[1] // groups, k[0], k[1]], "float32",
        attr=param_attr)
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], "float32", attr=bias_attr,
                             is_bias=True)
    from ...vision.ops import deform_conv2d as _dcn

    return _dcn(x, ensure_tensor(offset), w, bias=b, stride=stride,
                padding=padding, dilation=dilation,
                deformable_groups=deformable_groups, groups=groups,
                mask=None if (mask is None or not modulated)
                else ensure_tensor(mask))


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out[b, k] = x[b] . W[k] . y[b] + bias (reference
    bilinear_tensor_product)."""
    from ...ops import math as m

    xv, yv = ensure_tensor(x), ensure_tensor(y)
    dx, dy = xv.shape[-1], yv.shape[-1]
    w = create_parameter([size, dx, dy], "float32", attr=param_attr)
    from ...ops.linalg import einsum

    out = einsum("bi,kij,bj->bk", xv, w, yv)
    if bias_attr is not False:
        b = create_parameter([size], "float32", attr=bias_attr, is_bias=True)
        out = m.add(out, b)
    return _maybe_act(out, act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead convolution: out[t] = sum_k x[t+k] * w[k] (reference
    row_conv over [B, T, D])."""
    x = ensure_tensor(input)
    d = x.shape[-1]
    w = create_parameter([future_context_size + 1, d], "float32",
                         attr=param_attr)
    from ...core.tensor import apply

    out = apply("row_conv_p", x, w)
    return _maybe_act(out, act)


def _row_conv_fwd(xv, wv):
    import jax.numpy as jnp

    t = xv.shape[1]
    out = jnp.zeros_like(xv)
    for k in range(wv.shape[0]):
        out = out.at[:, : t - k, :].add(xv[:, k:, :] * wv[k])
    return out


_defprim("row_conv_p", _row_conv_fwd)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Weight normalized by its largest singular value, estimated with
    power iteration (reference static/nn/common.py spectral_norm)."""
    w = ensure_tensor(weight)
    layer = _nn.SpectralNorm(list(w.shape), dim=dim, power_iters=power_iters,
                             epsilon=eps)
    return layer(w)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static/nn/loss.py nce):
    logistic discrimination of the true class against sampled noise."""
    from ... import randint
    from ...ops import math as m
    from ...ops.manipulation import concat, gather, reshape

    x = ensure_tensor(input)
    lab = ensure_tensor(label)
    d = x.shape[-1]
    b = x.shape[0]
    k = int(num_neg_samples or 10)
    w = create_parameter([num_total_classes, d], "float32", attr=param_attr)
    bias = create_parameter([num_total_classes], "float32", attr=bias_attr,
                            is_bias=True)
    neg = randint(0, num_total_classes, [b, k])
    ids = concat([reshape(lab, [b, 1]), neg], axis=1)        # [B, 1+K]
    wsel = gather(w, reshape(ids, [-1]))                      # [B*(1+K), D]
    wsel = reshape(wsel, [b, 1 + k, d])
    bsel = reshape(gather(bias, reshape(ids, [-1])), [b, 1 + k])
    from ...ops.linalg import einsum

    logits = m.add(einsum("bd,bkd->bk", x, wsel), bsel)       # [B, 1+K]
    # positive gets label 1, sampled noise 0 — per-example logistic loss
    pos = logits[:, :1]
    negs = logits[:, 1:]
    lp = _nn.functional.log_sigmoid(pos)
    ln = _nn.functional.log_sigmoid(m.scale(negs, -1.0))
    return m.scale(m.add(m.sum(lp, axis=1), m.sum(ln, axis=1)), -1.0)
