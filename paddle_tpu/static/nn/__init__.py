"""``paddle.static.nn`` — layer functions + control flow for static graphs.

Reference: python/paddle/static/nn/__init__.py (38 exports). Layer
functions build the matching dynamic layer and apply it; control flow
maps onto Python/`lax` control flow (see control_flow.py); sequence ops
use packed (values, lengths) batches instead of LoD (see
sequence_lod.py).
"""
from ..extras import py_func  # noqa: F401
from .common import (  # noqa: F401
    batch_norm, conv2d, embedding, fc, group_norm, layer_norm, prelu,
    sparse_embedding,
)
from .control_flow import (  # noqa: F401
    case, cond, static_pylayer, switch_case, while_loop,
)
from .extra_layers import (  # noqa: F401
    bilinear_tensor_product, conv2d_transpose, conv3d, conv3d_transpose,
    data_norm, deform_conv2d, instance_norm, nce, row_conv, spectral_norm,
)
from .sequence_lod import (  # noqa: F401
    sequence_conv, sequence_enumerate, sequence_expand, sequence_expand_as,
    sequence_first_step, sequence_last_step, sequence_pad, sequence_pool,
    sequence_reshape, sequence_scatter, sequence_slice, sequence_softmax,
    sequence_unpad,
)

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate",
]
