"""Sequence ops over packed (values, lengths) batches.

Reference: python/paddle/static/nn/sequence_lod.py — those ops consume
LoD tensors (ragged batches encoded by offset tables). The TPU-first
redesign replaces LoD with an explicit dense representation: a batch of
sequences is a packed tensor ``x`` of shape [T, ...] (all rows
concatenated) plus an integer ``length`` vector [B] giving each
sequence's row count. Every op here takes ``length`` explicitly where
the reference would read LoD metadata; the math is expressed with
segment reductions and masked gathers so it stays static-shaped and
XLA-compilable wherever the output shape permits.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops._helpers import defprim, ensure_tensor
from ...core.tensor import Tensor

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_expand",
    "sequence_expand_as", "sequence_enumerate", "sequence_scatter",
    "sequence_slice",
]


def _seg_ids(length, total):
    """Row -> sequence index map: [T] int32 from lengths [B]."""
    ends = jnp.cumsum(length.astype(jnp.int32))
    return jnp.searchsorted(ends, jnp.arange(total, dtype=jnp.int32),
                            side="right").astype(jnp.int32)


def _starts(length):
    l = length.astype(jnp.int32)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(l)[:-1]])


def _valid_rows(length, total):
    return jnp.arange(total) < jnp.sum(length.astype(jnp.int32))


# ---------------------------------------------------------------------------
def _sequence_softmax_fwd(x, length):
    t = x.shape[0]
    seg = _seg_ids(length, t)
    b = length.shape[0]
    # segment max for stability, then segment-normalized exp
    mx = jax.ops.segment_max(x, seg, num_segments=b)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    e = jnp.exp(x - mx[seg])
    valid = _valid_rows(length, t)
    e = jnp.where(valid[(...,) + (None,) * (x.ndim - 1)], e, 0.0)
    s = jax.ops.segment_sum(e, seg, num_segments=b)
    return e / jnp.maximum(s[seg], 1e-30)


defprim("sequence_softmax_p", _sequence_softmax_fwd)


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    """Softmax within each sequence of a packed batch.

    Reference: static/nn/sequence_lod.py sequence_softmax (LoD level 0).
    ``length`` [B] is required (replaces LoD metadata).
    """
    x, l = _xl(input, length, "sequence_softmax")
    from ...core.tensor import apply

    return apply("sequence_softmax_p", x, l)


def _xl(input, length, opname):
    if length is None:
        raise ValueError(
            f"{opname} needs the per-sequence `length` vector: the TPU "
            "build uses packed (values, lengths) batches instead of LoD")
    return ensure_tensor(input), ensure_tensor(length)


# ---------------------------------------------------------------------------
def _sequence_pool_fwd(x, length, *, pool_type, pad_value):
    t = x.shape[0]
    b = length.shape[0]
    seg = _seg_ids(length, t)
    valid = _valid_rows(length, t)
    vmask = valid[(...,) + (None,) * (x.ndim - 1)]
    l = jnp.maximum(length.astype(x.dtype), 1)
    lshape = (b,) + (1,) * (x.ndim - 1)
    # empty sequences emit pad_value (reference sequence_pool semantics)
    empty = (length.astype(jnp.int32) == 0).reshape(lshape)
    pad = jnp.asarray(pad_value, x.dtype)
    if pool_type in ("sum", "average", "sqrt"):
        s = jax.ops.segment_sum(jnp.where(vmask, x, 0), seg, num_segments=b)
        if pool_type == "average":
            s = s / l.reshape(lshape)
        elif pool_type == "sqrt":
            s = s / jnp.sqrt(l).reshape(lshape)
        return jnp.where(empty, pad, s)
    if pool_type == "max":
        neg = jnp.asarray(-jnp.inf, x.dtype)
        m = jax.ops.segment_max(jnp.where(vmask, x, neg), seg,
                                num_segments=b)
        return jnp.where(empty, pad, jnp.where(jnp.isfinite(m), m, 0))
    if pool_type == "first":
        return jnp.where(empty, pad, x[_starts(length)])
    if pool_type == "last":
        idx = _starts(length) + jnp.maximum(
            length.astype(jnp.int32) - 1, 0)
        return jnp.where(empty, pad, x[idx])
    raise ValueError(f"unsupported pool_type: {pool_type}")


defprim("sequence_pool_p", _sequence_pool_fwd)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None, name=None):
    """Per-sequence reduction: sum/average/sqrt/max/first/last.

    Reference: static/nn/sequence_lod.py sequence_pool."""
    x, l = _xl(input, length, "sequence_pool")
    from ...core.tensor import apply

    return apply("sequence_pool_p", x, l, pool_type=str(pool_type).lower(),
                 pad_value=float(pad_value))


def sequence_first_step(input, length=None, name=None):
    """First row of each sequence (reference sequence_first_step)."""
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None, name=None):
    """Last row of each sequence (reference sequence_last_step)."""
    return sequence_pool(input, "last", length=length)


# ---------------------------------------------------------------------------
def _sequence_pad_fwd(x, pad_value, length, *, maxlen):
    b = length.shape[0]
    starts = _starts(length)
    idx = starts[:, None] + jnp.arange(maxlen)[None, :]          # [B, L]
    in_range = jnp.arange(maxlen)[None, :] < length[:, None]
    gathered = x[jnp.clip(idx, 0, x.shape[0] - 1)]               # [B, L, ...]
    pad = jnp.broadcast_to(
        pad_value.astype(x.dtype).reshape((1, 1) + pad_value.shape),
        gathered.shape) if pad_value.ndim else pad_value.astype(x.dtype)
    mask = in_range[(...,) + (None,) * (x.ndim - 1)]
    return jnp.where(mask, gathered, pad), length.astype(jnp.int64)


defprim("sequence_pad_p", _sequence_pad_fwd, multi_out=True)


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pack [T, ...] + lengths -> padded [B, maxlen, ...] and lengths.

    Reference: static/nn/sequence_lod.py sequence_pad (returns the padded
    tensor and the original lengths)."""
    xv, l = _xl(x, length, "sequence_pad")
    pv = ensure_tensor(pad_value)
    if maxlen is None:
        maxlen = int(np.asarray(jnp.max(l._value)))
    from ...core.tensor import apply

    return apply("sequence_pad_p", xv, pv, l, maxlen=int(maxlen))


def sequence_unpad(x, length, name=None):
    """Padded [B, L, ...] + lengths -> packed [T, ...].

    Reference: static/nn/sequence_lod.py sequence_unpad. The output row
    count is data-dependent, so this op requires concrete lengths
    (eager; under to_static the eager-fallback path handles it)."""
    xv = ensure_tensor(x)
    l = ensure_tensor(length)
    lens = np.asarray(l._value).astype(np.int64).reshape(-1)
    from ...ops.manipulation import concat

    rows = [xv[int(i), : int(n)] for i, n in enumerate(lens)]
    return concat(rows, axis=0)


def sequence_reshape(input, new_dim, name=None):
    """Re-chunk packed rows to width new_dim (reference sequence_reshape)."""
    x = ensure_tensor(input)
    from ...ops.manipulation import reshape

    return reshape(x, [-1, int(new_dim)])


# ---------------------------------------------------------------------------
def sequence_expand(x, y, ref_level=-1, length=None, y_length=None,
                    name=None):
    """Repeat each sequence of x per the matching sequence count in y.

    Reference: static/nn/sequence_lod.py sequence_expand. Dense form:
    sequence i of x (lengths ``length``) is tiled ``y_length[i]`` times.
    Output row count is data-dependent -> concrete lengths required."""
    xv, l = _xl(x, length, "sequence_expand")
    if y_length is None:
        raise ValueError("sequence_expand needs y_length (expand counts)")
    counts = np.asarray(ensure_tensor(y_length)._value).astype(
        np.int64).reshape(-1)
    lens = np.asarray(l._value).astype(np.int64).reshape(-1)
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
    from ...ops.manipulation import concat

    chunks = []
    for i, c in enumerate(counts):
        seq = xv[int(starts[i]): int(starts[i] + lens[i])]
        chunks.extend([seq] * int(max(c, 0)))
    return concat(chunks, axis=0)


def sequence_expand_as(x, y, length=None, y_length=None, name=None):
    """Tile row i of x to the length of sequence i in y.

    Reference: static/nn/sequence_lod.py sequence_expand_as."""
    xv = ensure_tensor(x)
    if y_length is None:
        raise ValueError("sequence_expand_as needs y_length")
    counts = np.asarray(ensure_tensor(y_length)._value).astype(
        np.int64).reshape(-1)
    from ...ops.manipulation import concat

    chunks = [xv[i: i + 1].tile([int(c)] + [1] * (len(xv.shape) - 1))
              for i, c in enumerate(counts)]
    return concat(chunks, axis=0)


# ---------------------------------------------------------------------------
def _sequence_enumerate_fwd(x, length, *, win_size, pad_value):
    t = x.shape[0]
    seg = _seg_ids(length, t)
    idx = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]  # [T, W]
    safe = jnp.clip(idx, 0, t - 1)
    same_seq = (seg[safe] == seg[:, None]) & (idx < t)
    vals = x[safe]
    return jnp.where(same_seq, vals, jnp.asarray(pad_value, x.dtype))


defprim("sequence_enumerate_p", _sequence_enumerate_fwd)


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    """Sliding windows that do not cross sequence boundaries.

    Reference: static/nn/sequence_lod.py sequence_enumerate."""
    x, l = _xl(input, length, "sequence_enumerate")
    from ...core.tensor import apply

    return apply("sequence_enumerate_p", x, l, win_size=int(win_size),
                 pad_value=int(pad_value))


# ---------------------------------------------------------------------------
def _sequence_scatter_fwd(x, index, updates, length):
    # x: [B, D]; index/updates packed rows, sequence i of the packed pair
    # scatters into row i of x (reference sequence_scatter LoD semantics)
    t = index.shape[0]
    seg = _seg_ids(length, t)
    valid = _valid_rows(length, t)
    upd = jnp.where(valid[(...,) + (None,) * (updates.ndim - 1)], updates, 0)
    return x.at[seg, index.astype(jnp.int32)].add(upd)


defprim("sequence_scatter_p", _sequence_scatter_fwd)


def sequence_scatter(input, index, updates, length=None, name=None):
    """Scatter-add packed per-sequence updates into rows of input.

    Reference: static/nn/sequence_lod.py sequence_scatter."""
    x = ensure_tensor(input)
    idx, l = _xl(index, length, "sequence_scatter")
    from ...core.tensor import apply

    return apply("sequence_scatter_p", x, idx, ensure_tensor(updates), l)


def sequence_slice(input, offset, length, seq_length=None, name=None):
    """Per-sequence slice [offset : offset+length] of a packed batch.

    Reference: static/nn/sequence_lod.py sequence_slice. Output row count
    is data-dependent -> concrete values required."""
    xv, sl = _xl(input, seq_length, "sequence_slice")
    offs = np.asarray(ensure_tensor(offset)._value).astype(np.int64).reshape(-1)
    lens = np.asarray(ensure_tensor(length)._value).astype(np.int64).reshape(-1)
    seq = np.asarray(sl._value).astype(np.int64).reshape(-1)
    starts = np.concatenate([[0], np.cumsum(seq)[:-1]])
    from ...ops.manipulation import concat

    return concat([xv[int(s + o): int(s + o + n)]
                   for s, o, n in zip(starts, offs, lens)], axis=0)


# ---------------------------------------------------------------------------
def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, length=None, name=None):
    """Context-window convolution within sequence boundaries.

    Reference: static/nn/sequence_lod.py sequence_conv (context windows
    gathered per row, zero beyond the sequence edge, then projected)."""
    x, l = _xl(input, length, "sequence_conv")
    d = x.shape[-1]
    from ...framework.misc import create_parameter

    w = create_parameter([int(filter_size) * d, int(num_filters)],
                         dtype=str(x.dtype), attr=param_attr)
    bias = None
    if bias_attr is not False:
        bias = create_parameter([int(num_filters)], dtype=str(x.dtype),
                                attr=bias_attr, is_bias=True)
    from ...core.tensor import apply

    if padding_start is None:
        padding_start = -(int(filter_size) // 2)
    ctx = apply("sequence_conv_ctx_p", x, l,
                filter_size=int(filter_size),
                padding_start=int(padding_start))
    from ...ops.math import matmul, add

    out = matmul(ctx, w)
    if bias is not None:
        out = add(out, bias)
    from .common import _maybe_act

    return _maybe_act(out, act)


def _sequence_conv_ctx_fwd(x, length, *, filter_size, padding_start):
    t, d = x.shape
    seg = _seg_ids(length, t)
    offs = jnp.arange(filter_size) + padding_start                # [W]
    idx = jnp.arange(t)[:, None] + offs[None, :]                  # [T, W]
    safe = jnp.clip(idx, 0, t - 1)
    ok = (idx >= 0) & (idx < t) & (seg[safe] == seg[:, None])
    vals = jnp.where(ok[..., None], x[safe], 0)                   # [T, W, D]
    return vals.reshape(t, filter_size * d)


defprim("sequence_conv_ctx_p", _sequence_conv_ctx_fwd)
