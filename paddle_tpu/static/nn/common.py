"""``paddle.static.nn`` — layer functions for static-graph programs.

Reference: python/paddle/static/nn/common.py (fc :~, conv2d, batch_norm,
embedding, ...). Under this framework's capture model the dynamic layers
already record into the active Program, so these functions build the layer
once (parameters register with the startup program's initialization) and
apply it to the placeholder value."""
from __future__ import annotations

import numpy as np

from ... import nn as _nn
from ...ops._helpers import ensure_tensor
def _maybe_act(out, act):
    if act is not None:
        out = getattr(_nn.functional, act)(out)
    return out


__all__ = ["fc", "embedding", "conv2d", "batch_norm", "layer_norm",
           "sparse_embedding", "prelu", "group_norm"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    x = ensure_tensor(x)
    from ...ops.manipulation import reshape

    in_features = int(np.prod(x.shape[num_flatten_dims:]))
    if x.ndim > num_flatten_dims + 1:
        x = reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_features,))
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    return _maybe_act(layer(x), activation)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    if dtype is not None and str(np.dtype(dtype)) != "float32":
        from ...ops.math import cast

        layer.weight._replace_value(cast(layer.weight, dtype)._value)
    return layer(ensure_tensor(input))


# the reference's distributed lookup-table embedding; dense layout is the
# TPU-native storage (the PS-backed variant lives in distributed.ps)
sparse_embedding = embedding


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    x = ensure_tensor(input)
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn.Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _maybe_act(layer(x), act)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    x = ensure_tensor(input)
    channels = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    layer = _nn.BatchNorm2D(channels, momentum=momentum, epsilon=epsilon,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_layout)
    if is_test:
        layer.eval()
    return _maybe_act(layer(x), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    x = ensure_tensor(input)
    normalized_shape = list(x.shape[begin_norm_axis:])
    layer = _nn.LayerNorm(
        normalized_shape, epsilon=epsilon,
        weight_attr=param_attr if scale else False,
        bias_attr=bias_attr if shift else False,
    )
    return _maybe_act(layer(x), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    x = ensure_tensor(input)
    channels = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    layer = _nn.GroupNorm(groups, channels, epsilon=epsilon,
                          weight_attr=param_attr, bias_attr=bias_attr,
                          data_format=data_layout)
    return _maybe_act(layer(x), act)


class _ElementwisePReLU(_nn.Layer):
    """One alpha per (non-batch) element — the reference's mode='element'."""

    def __init__(self, shape, weight_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            list(shape), attr=weight_attr,
            default_initializer=_nn.initializer.Constant(0.25),
        )

    def forward(self, x):
        from ...ops.math import maximum, minimum

        return maximum(x, 0.0) + self.weight * minimum(x, 0.0)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    elif mode == "element":
        return _ElementwisePReLU(x.shape[1:], weight_attr=param_attr)(x)
    else:
        raise ValueError(
            f"mode should be 'all', 'channel' or 'element', but got {mode!r}"
        )
    layer = _nn.PReLU(num_parameters=num, weight_attr=param_attr,
                      data_format=data_format)
    return layer(x)
