"""paddle.jit — whole-function capture to XLA.

Reference: python/paddle/jit/api.py:196 to_static (SOT bytecode translator,
program_translator.py:711) + jit.save/load (api.py:953/:1523).

TPU re-design: the reference needs a CPython eval-frame interpreter to build
a static program from dygraph code; here the eager Tensor already wraps jax
values, so capture is plain jax tracing — the same user function runs on
tracers and the recorded tape/ops become one XLA program. Guards collapse to
a cache key over input avals + layer modes (the SOT guard system's shape/
type guards, executor/guard.py).

Crucially this compiles ENTIRE TRAIN STEPS: parameters, buffers, optimizer
accumulators and RNG are lifted to functional state (inputs + outputs of the
jitted program, donated for in-place buffer reuse), so `loss.backward()` and
`opt.step()` inside the captured function fuse into one XLA executable —
this is the eager-dispatch-cost answer flagged in SURVEY §7.
"""
from __future__ import annotations

import functools
import inspect
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..core import generator
from ..core.tensor import Parameter, Tensor
from .trace_state import in_tracing, tracing_scope

_M_JIT_COMPILES = _obs.counter(
    "jit.compiles", "to_static compiles (new input-signature cache entry)")
_M_JIT_HITS = _obs.counter(
    "jit.cache_hits", "to_static calls served by an existing entry")
_M_JIT_COMPILE_SECONDS = _obs.histogram(
    "jit.compile_seconds",
    "wall time of a to_static entry's first run (trace + XLA compile)")
_M_JIT_FALLBACKS = _obs.counter(
    "jit.fallbacks", "to_static signatures that fell back to eager")

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "TracedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


# --------------------------------------------------------------------------
# state slots
# --------------------------------------------------------------------------
class _TensorSlot:
    """A mutable Tensor owned by a Layer (param or buffer) lifted to
    functional state."""

    __slots__ = ("t",)

    def __init__(self, t: Tensor):
        self.t = t

    def get(self):
        return self.t._value

    def set(self, v):
        self.t._replace_value(v)


class _AccumSlot:
    __slots__ = ("opt", "name", "pid")

    def __init__(self, opt, name, pid):
        self.opt, self.name, self.pid = opt, name, pid

    def get(self):
        return self.opt._accumulators[self.name][self.pid]

    def set(self, v):
        self.opt._accumulators[self.name][self.pid] = v


class _MasterSlot:
    __slots__ = ("opt", "pid")

    def __init__(self, opt, pid):
        self.opt, self.pid = opt, pid

    def get(self):
        return self.opt._master_weights[self.pid]

    def set(self, v):
        self.opt._master_weights[self.pid] = v


def _closure_objects(fn):
    objs = []
    if hasattr(fn, "__self__") and fn.__self__ is not None:
        objs.append(fn.__self__)
    closure = getattr(fn, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                objs.append(cell.cell_contents)
            except ValueError:
                pass
    # module-level globals the function references by name
    code = getattr(fn, "__code__", None)
    if code is not None:
        g = getattr(fn, "__globals__", {})
        for name in code.co_names:
            if name in g:
                objs.append(g[name])
    return objs


def _discover(fn, args, kwargs):
    """Find Layers and Optimizers the function touches (self, closure cells,
    positional args) — the dygraph-module discovery the reference does via
    its bytecode walker."""
    from ..nn.layer import Layer
    from ..optimizer.optimizer import Optimizer

    import types

    layers: List[Any] = []
    optimizers: List[Any] = []
    seen = set()

    def visit(o, depth=0):
        if id(o) in seen or depth > 6:
            return
        seen.add(id(o))
        if isinstance(o, Layer):
            layers.append(o)
        elif isinstance(o, Optimizer):
            optimizers.append(o)
        elif isinstance(o, (list, tuple)):
            for x in o:
                visit(x, depth + 1)
        elif isinstance(o, dict):
            for x in o.values():
                visit(x, depth + 1)
        elif isinstance(o, types.FunctionType):
            # nested helper closures (e.g. a step fn calling a local
            # forward fn that holds the model)
            for c in _closure_objects(o):
                visit(c, depth + 1)
        elif hasattr(o, "__dict__") and not isinstance(
            o, (Tensor, type, types.ModuleType)
        ):
            # plain containers (wrapper objects like DistModel) — scan
            # their attributes for Layers/Optimizers
            for x in vars(o).values():
                visit(x, depth + 1)

    for o in _closure_objects(fn):
        visit(o)
    for a in list(args) + list(kwargs.values()):
        visit(a)
    return layers, optimizers


# --------------------------------------------------------------------------
# pytree over Tensors
# --------------------------------------------------------------------------
def _flatten_args(obj, arrays: List[Any]):
    """Returns a hashable template; Tensor leaves become ('T', idx, sg)."""
    if isinstance(obj, Tensor):
        arrays.append(obj._value)
        return ("T", len(arrays) - 1, bool(obj.stop_gradient))
    if isinstance(obj, (list, tuple)):
        return (
            "L" if isinstance(obj, list) else "t",
            tuple(_flatten_args(o, arrays) for o in obj),
        )
    if isinstance(obj, dict):
        return (
            "D",
            tuple(sorted((k, _flatten_args(v, arrays)) for k, v in obj.items())),
        )
    if isinstance(obj, (int, float, str, bool, type(None), np.integer, np.floating)):
        return ("C", obj)
    if isinstance(obj, np.ndarray):
        arrays.append(jnp.asarray(obj))
        return ("T", len(arrays) - 1, True)
    # opaque static object (Layer/Optimizer instance etc.): key by identity
    return ("O", id(obj))


def _unflatten_args(template, arrays, objs_by_id):
    kind = template[0]
    if kind == "T":
        t = Tensor._from_value(arrays[template[1]], stop_gradient=template[2])
        return t
    if kind in ("L", "t"):
        seq = [_unflatten_args(t_, arrays, objs_by_id) for t_ in template[1]]
        return seq if kind == "L" else tuple(seq)
    if kind == "D":
        return {k: _unflatten_args(v, arrays, objs_by_id) for k, v in template[1]}
    if kind == "C":
        return template[1]
    return objs_by_id[template[1]]


def _flatten_out(obj, arrays: List[Any]):
    if isinstance(obj, Tensor):
        arrays.append(obj._value)
        return ("T", len(arrays) - 1, bool(obj.stop_gradient))
    if isinstance(obj, (list, tuple)):
        return (
            "L" if isinstance(obj, list) else "t",
            tuple(_flatten_out(o, arrays) for o in obj),
        )
    if isinstance(obj, dict):
        return ("D", tuple((k, _flatten_out(v, arrays)) for k, v in obj.items()))
    return ("C", obj)


def _unflatten_out(template, arrays):
    kind = template[0]
    if kind == "T":
        return Tensor._from_value(arrays[template[1]], stop_gradient=template[2])
    if kind in ("L", "t"):
        seq = [_unflatten_out(t_, arrays) for t_ in template[1]]
        return seq if kind == "L" else tuple(seq)
    if kind == "D":
        return {k: _unflatten_out(v, arrays) for k, v in template[1]}
    return template[1]


def _aval_key(arrays):
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


class _CompiledEntry:
    __slots__ = ("jitted", "slots", "out_template_box", "optimizers",
                 "step_deltas", "fallback", "ran_ok")

    def __init__(self):
        self.jitted = None
        self.slots = []
        self.out_template_box = [None]
        self.optimizers = []
        self.step_deltas = []
        self.fallback = False
        self.ran_ok = False


class StaticFunction:
    """The compiled-function cache (reference: program_translator.py
    ProgramCache keyed by guards; here keyed by input avals + layer modes)."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 full_graph=False, donate_state: bool = True):
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._cache: Dict[Any, _CompiledEntry] = {}
        self._donate = donate_state
        self._input_spec = input_spec
        self._full_graph = full_graph

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._fn.__get__(instance, owner),
                               self._input_spec, full_graph=self._full_graph,
                               donate_state=self._donate)
        # cache the bound wrapper on the instance
        name = self._fn.__name__
        try:
            object.__setattr__(instance, name, bound)
        except Exception:
            pass
        return bound

    # ------------------------------------------------------------------
    def _mode_key(self, layers):
        return tuple(l.training for l in layers)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled or in_tracing():
            return self._fn(*args, **kwargs)
        layers, optimizers = _discover(self._fn, args, kwargs)
        arrays: List[Any] = []
        template = _flatten_args((args, kwargs), arrays)
        key = (template, _aval_key(arrays), self._mode_key(layers),
               tuple(id(o) for o in optimizers))
        entry = self._cache.get(key)
        fn_label = getattr(self._fn, "__name__", "?")
        if entry is None:
            if _obs.state.on:
                _M_JIT_COMPILES.inc(fn=fn_label)
            entry = self._compile(template, arrays, layers, optimizers, args, kwargs)
            self._cache[key] = entry
        elif _obs.state.on:
            _M_JIT_HITS.inc(fn=fn_label)
        if entry.fallback:
            # counted once at the transition below, not per call
            return self._fn(*args, **kwargs)
        # runtime invocation
        state = [s.get() for s in entry.slots]
        lr_vals = jnp.asarray(
            [o.get_lr() for o in entry.optimizers], jnp.float32
        ) if entry.optimizers else jnp.zeros((0,), jnp.float32)
        steps = jnp.asarray(
            [o._step_count + 1 for o in entry.optimizers], jnp.float32
        ) if entry.optimizers else jnp.zeros((0,), jnp.float32)
        rng = generator.next_key("local_seed")
        first_run = not entry.ran_ok  # first run pays jax trace + XLA compile
        t0 = time.perf_counter()
        try:
            out_arrays, new_state = entry.jitted(state, arrays, rng, lr_vals,
                                                 steps)
        except Exception as e:  # noqa: BLE001 — SOT-style graph break
            # Reference contract (jit/sot program_translator.py:711): an
            # untraceable construct (data-dependent Python control flow,
            # reverse-mode through a while_loop, ...) must not crash the
            # user's function — fall back to eager for this signature.
            # Only TRACE-time failures fall back: if tracing succeeded and
            # XLA execution itself failed, the input state buffers may
            # already be donated/deleted, and the real error (OOM, nan
            # check) must surface, not be masked by an eager rerun. Note
            # the failed trace already ran the function's Python body, so
            # Python-level side effects execute twice on a fallback call.
            if self._full_graph or entry.ran_ok:
                raise
            if "XlaRuntimeError" in type(e).__name__:
                raise
            import warnings

            warnings.warn(
                f"to_static: tracing '{getattr(self._fn, '__name__', '?')}' "
                f"failed ({type(e).__name__}: {e}); falling back to eager "
                "execution for this input signature. Pass full_graph=True "
                "to make this an error.")
            entry.fallback = True
            if _obs.state.on:
                _M_JIT_FALLBACKS.inc(fn=fn_label)
            return self._fn(*args, **kwargs)
        entry.ran_ok = True
        if first_run and _obs.state.on:
            dt = time.perf_counter() - t0
            _M_JIT_COMPILE_SECONDS.observe(dt, fn=fn_label)
            _obs.emit("jit.compile", fn=fn_label, seconds=dt,
                      n_inputs=len(arrays), n_state=len(entry.slots))
        for s, v in zip(entry.slots, new_state):
            s.set(v)
        # replay python-side step-count increments observed at trace time
        for o, d in zip(entry.optimizers, entry.step_deltas):
            o._step_count += d
        return _unflatten_out(entry.out_template_box[0], out_arrays)

    # ------------------------------------------------------------------
    def _compile(self, template, arrays, layers, optimizers, args, kwargs):
        entry = _CompiledEntry()
        entry.optimizers = optimizers
        slots: List[Any] = []
        slot_ids = set()

        def add_slot(s, key_id):
            if key_id in slot_ids:
                return
            slot_ids.add(key_id)
            slots.append(s)

        for l in layers:
            for _, p in l.named_parameters():
                add_slot(_TensorSlot(p), id(p))
            for _, b in l.named_buffers():
                add_slot(_TensorSlot(b), id(b))
        for o in optimizers:
            # ensure accumulators/masters exist before lifting: run a dummy
            # discovery pass — accumulators appear lazily on first step(); to
            # keep first-call compile correct we pre-create via _accum on
            # trainable params using the optimizer's own step-0 path.
            o._ensure_accumulators()
            for p in o._parameter_list:
                if isinstance(p, Tensor):
                    add_slot(_TensorSlot(p), id(p))
            for name, store in o._accumulators.items():
                for pid in store:
                    add_slot(_AccumSlot(o, name, pid), (id(o), name, pid))
            for pid in o._master_weights:
                add_slot(_MasterSlot(o, pid), (id(o), "master", pid))
        entry.slots = slots

        objs_by_id = {}

        def collect_ids(obj):
            if isinstance(obj, (list, tuple)):
                for x in obj:
                    collect_ids(x)
            elif isinstance(obj, dict):
                for x in obj.values():
                    collect_ids(x)
            elif not isinstance(
                obj, (Tensor, int, float, str, bool, type(None), np.ndarray,
                      np.integer, np.floating)
            ):
                objs_by_id[id(obj)] = obj

        collect_ids((args, kwargs))

        fn = self._fn
        out_box = entry.out_template_box

        def pure_fn(state, arg_arrays, rng, lr_vals, steps):
            originals = [s.get() for s in slots]
            grads_snapshot = [
                (s.t, s.t._grad_value) for s in slots if isinstance(s, _TensorSlot)
            ]
            lr_prev = [(o, o._lr_override, o._step_override) for o in optimizers]
            pre_counts = [o._step_count for o in optimizers]
            try:
                for s, v in zip(slots, state):
                    s.set(v)
                for i, o in enumerate(optimizers):
                    o._lr_override = lr_vals[i]
                    o._step_override = steps[i]
                with tracing_scope(), generator.trace_key_scope(rng):
                    a2, k2 = _unflatten_args(template, arg_arrays, objs_by_id)
                    out = fn(*a2, **k2)
                out_arrays: List[Any] = []
                out_box[0] = _flatten_out(out, out_arrays)
                new_state = [s.get() for s in slots]
                return out_arrays, new_state
            finally:
                for s, v in zip(slots, originals):
                    s.set(v)
                for t, g in grads_snapshot:
                    t._grad_value = g
                for o, lro, so in lr_prev:
                    o._lr_override = lro
                    o._step_override = so
                entry.step_deltas = [
                    o._step_count - c for o, c in zip(optimizers, pre_counts)
                ]
                for o, c in zip(optimizers, pre_counts):
                    o._step_count = c

        donate = (0,) if self._donate else ()
        entry.jitted = jax.jit(pure_fn, donate_argnums=donate)
        return entry

    @property
    def code(self):
        import textwrap

        return textwrap.dedent(inspect.getsource(self._fn))

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """paddle.jit.to_static parity (api.py:196). full_graph=False (the
    reference SOT default) falls back to eager when tracing fails;
    full_graph=True surfaces trace errors."""

    def decorate(fn):
        from ..nn.layer import Layer

        if isinstance(fn, Layer):
            layer = fn
            static = StaticFunction(layer.forward, input_spec,
                                    full_graph=full_graph)
            layer.forward = static
            return layer
        return StaticFunction(fn, input_spec, full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


class TracedLayer:
    pass


# --------------------------------------------------------------------------
# save / load — export a traced inference program (StableHLO) + params.
# Reference: jit/api.py:953 jit.save (program+params for AnalysisPredictor),
# jit/api.py:1523 jit.load.
# --------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    """Serialize layer params + an exported StableHLO forward (when
    input_spec with concrete shapes is given)."""
    import pickle

    from ..framework.io_ import _pack
    from ..nn.layer import Layer

    payload: Dict[str, Any] = {}
    if isinstance(layer, Layer):
        payload["state_dict"] = _pack(layer.state_dict())
        if input_spec:
            specs = []
            for s in input_spec:
                if isinstance(s, Tensor):
                    specs.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
                elif isinstance(s, InputSpec):
                    specs.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
            was_training = layer.training
            layer.eval()

            def fwd(*xs):
                outs = layer(*[Tensor._from_value(x) for x in xs])
                if isinstance(outs, Tensor):
                    return outs._value
                return [o._value for o in outs]

            try:
                exported = jax.export.export(jax.jit(fwd))(*specs)
                payload["stablehlo"] = exported.mlir_module()
                payload["serialized"] = bytes(exported.serialize())
                payload["in_specs"] = [(tuple(s.shape), str(s.dtype)) for s in specs]
            except Exception as e:  # export is best-effort; params always saved
                payload["export_error"] = repr(e)
            finally:
                # saving must not flip the live model's train/eval state
                if was_training:
                    layer.train()
    else:
        payload["state_dict"] = _pack(layer)
    with open(path + (".pdmodel" if not path.endswith(".pdmodel") else ""), "wb") as f:
        pickle.dump(payload, f, protocol=4)
    return payload


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..core.dtype import convert_dtype

        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient


class _LoadedFunction:
    def __init__(self, payload):
        import pickle

        self._payload = payload
        self._state = payload.get("state_dict", {})
        self._callable = None
        if "serialized" in payload:
            exported = jax.export.deserialize(bytearray(payload["serialized"]))
            self._callable = exported.call

    def __call__(self, *args):
        if self._callable is None:
            raise RuntimeError("loaded program has no executable graph")
        arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._callable(*arrays)
        if isinstance(out, (list, tuple)):
            return [Tensor._from_value(o) for o in out]
        return Tensor._from_value(out)

    def state_dict(self):
        from ..framework.io_ import _unpack

        return _unpack(self._state)


def load(path, **configs):
    import pickle

    p = path if path.endswith(".pdmodel") else path + ".pdmodel"
    with open(p, "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload)


class TranslatedLayer(_LoadedFunction):
    """Reference: jit/translated_layer.py:1285 — the Layer-like object
    jit.load returns: callable, exposes state_dict/parameters, eval/train
    toggles.

    Limitation vs the reference: the loaded program is a serialized
    StableHLO executable with baked weights, so optimizer updates on
    parameters() do NOT feed back into __call__ — the artifact is an
    inference program (the reference's fine-tune path re-executes the
    stored ProgramDesc, which this build does not reconstruct)."""

    def __init__(self, payload):
        super().__init__(payload)
        self.training = False
        self._parameters_cache = None

    def forward(self, *args):
        return self(*args)

    def parameters(self, include_sublayers=True):
        from ..core.tensor import Parameter

        if self._parameters_cache is None:
            # stable identity: repeated calls return the same objects
            self._parameters_cache = [
                v if isinstance(v, Parameter)
                else Parameter(v._value if hasattr(v, "_value") else v)
                for v in self.state_dict().values()
            ]
        return list(self._parameters_cache)

    def eval(self):
        self.training = False
        return self

    def train(self):
        self.training = True
        return self


_SOT_CODE_LEVEL = 0
_SOT_VERBOSITY = 0


def set_code_level(level=100, also_to_stdout=False):
    """Reference: jit/sot/utils/code_status.py via paddle.jit.set_code_level
    — bytecode-translation logging. The TPU build traces through jax (no
    bytecode simulation); the level gates trace-cache debug output."""
    global _SOT_CODE_LEVEL
    _SOT_CODE_LEVEL = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    """Reference: paddle.jit.set_verbosity — dy2static logging level."""
    global _SOT_VERBOSITY
    _SOT_VERBOSITY = int(level)


__all__.extend(["TranslatedLayer", "set_code_level", "set_verbosity"])
