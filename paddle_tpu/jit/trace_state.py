"""Tracing-state flag shared between eager code and jit capture."""
from __future__ import annotations

import threading

_state = threading.local()


def in_tracing() -> bool:
    return getattr(_state, "tracing", False)


class tracing_scope:
    def __enter__(self):
        self._prev = in_tracing()
        _state.tracing = True
        return self

    def __exit__(self, *exc):
        _state.tracing = self._prev
        return False
