"""Inference engine.

Reference: paddle/fluid/inference/ — AnalysisPredictor
(api/analysis_predictor.h: load a saved program, run the IR pass
pipeline, execute), paddle.inference.Config + create_predictor
(python/paddle/inference/).

TPU re-design: the saved artifact is jit.save's payload (state_dict +
serialized StableHLO from jax.export). "Analysis passes" are XLA — the
deserialized executable is already optimized for the target; the
predictor's job is name-based input/output plumbing, exactly the
AnalysisPredictor surface.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """Reference: paddle.inference.Config — model path + device/runtime
    knobs. TPU knobs map to XLA/jit; CUDA-specific toggles are accepted
    and ignored for API parity."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._prog_file = prog_file
        self._params_file = params_file
        self._device = "tpu"
        self._memory_pool_init_size_mb = 0
        self._enable_profile = False
        self._glog_info = False
        self._ir_optim = True
        self._memory_optim = False

    def set_prog_file(self, path: str):
        self._prog_file = path

    def prog_file(self):
        return self._prog_file

    def set_params_file(self, path: str):
        self._params_file = path

    def params_file(self):
        return self._params_file

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        self._device = "tpu"  # accelerator path; XLA owns memory

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def enable_profile(self):
        self._enable_profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def switch_ir_optim(self, on: bool = True):
        """Run the analysis pass pipeline (constant folding, add+act
        fusion, dead-code elimination) on loaded STATIC programs before
        execution (reference: AnalysisConfig::SwitchIrOptim driving
        inference/analysis/). jit.save StableHLO artifacts arrive
        pre-optimized by XLA and are unaffected."""
        self._ir_optim = bool(on)

    def ir_optim(self) -> bool:
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        """Donate input buffers to the compiled executable so XLA reuses
        them for outputs/temps (reference: Config::EnableMemoryOptim's
        variable-reuse pass)."""
        self._memory_optim = bool(x)

    def memory_optim_enabled(self) -> bool:
        return self._memory_optim

    def summary(self) -> str:
        return (f"Config(prog_file={self._prog_file}, "
                f"device={self._device}, ir_optim={self._ir_optim}, "
                f"memory_optim={self._memory_optim})")


class PredictorTensor:
    """Name-addressed input/output handle (reference:
    paddle.inference Tensor / ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._inputs[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._outputs[self.name])

    def shape(self):
        if self._is_input:
            return list(self._owner._inputs[self.name].shape)
        return list(np.asarray(self._owner._outputs[self.name]).shape)


class Predictor:
    """Reference: AnalysisPredictor. Loads either artifact kind:

    - a jit.save payload (state_dict + StableHLO): executed as-is (XLA
      already optimized it at export);
    - a static.save program (.pdmodel instruction list + .pdparams):
      the ANALYSIS PIPELINE runs first when config.ir_optim() —
      constant folding, add+act fusion, then dead-code elimination to
      the saved fetch targets — the inference/analysis/ pass pipeline
      on the TPU program representation. enable_memory_optim() donates
      input buffers to the compiled executable.
    """

    def __init__(self, config: Config):
        self._config = config
        self._loaded = None
        self._program = None
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []
        self.analysis_passes_applied: List[str] = []

        from .static.extras import load_static_artifact

        prog = load_static_artifact(config.prog_file(),
                                    params_file=config.params_file())
        if prog is not None:
            self._init_static(config, prog)
        else:
            self._init_stablehlo(config)

    def _init_stablehlo(self, config):
        from . import jit

        self._loaded = jit.load(config.prog_file())
        in_specs = self._loaded._payload.get("in_specs") or []
        self._input_names = [f"x{i}" for i in range(len(in_specs))]
        self._in_specs = in_specs

    def _init_static(self, config, prog):
        from .distributed.passes import PassManager, new_pass

        fetch_vids = list(getattr(prog, "_fetch_vids", ()) or ())
        if not fetch_vids and prog._insts:
            fetch_vids = list(prog._insts[-1][3])  # last op's outputs
        if config.ir_optim():
            pm = PassManager([
                new_pass("constant_folding"),
                new_pass("fuse_elewise_add_act", {"fetch": fetch_vids}),
                new_pass("dead_code_elimination", {"fetch": fetch_vids}),
            ])
            pm.apply(prog, None)
            self.analysis_passes_applied = list(pm.names)
        self._program = prog
        self._fetch_vids = tuple(fetch_vids)
        self._input_names = [name for name, _vid, _shape, _dt
                             in prog._placeholders]

    # -- AnalysisPredictor surface --------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, is_input=True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, is_input=False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Either positional (returns outputs) or handle-based like the
        reference's zero-copy flow."""
        if inputs is not None:
            arrays = [np.asarray(a) for a in inputs]
        else:
            arrays = [self._inputs[n] for n in self._input_names]
        if self._program is not None:
            outs = self._run_static(arrays)
        else:
            outs = self._loaded(*arrays)
            if isinstance(outs, Tensor):
                outs = [outs]
            outs = [np.asarray(o._value) for o in outs]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = dict(zip(self._output_names, outs))
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return None

    def _run_static(self, arrays):
        import jax

        from .static.program import Executor

        prog = self._program
        feed_names = tuple(self._input_names)
        donate = (self._config.memory_optim_enabled()
                  and jax.default_backend() != "cpu")
        # the fingerprint keeps this correct now that rewrite passes
        # re-fingerprint instead of clearing prog._cache
        key = ("__infer__", prog.fingerprint(),
               tuple((a.shape, str(a.dtype)) for a in arrays),
               self._fetch_vids, donate)
        fn = prog._cache.get(key)
        if fn is None:
            fn = Executor._compile(prog, feed_names, self._fetch_vids,
                                   donate=donate)
        else:
            prog._cache.pop(key)  # LRU refresh vs Executor.run eviction
        prog._cache[key] = fn
        outs = fn(*arrays)
        return [np.asarray(o) for o in outs]

    def get_program(self):
        """The (possibly pass-optimized) static Program, when the loaded
        artifact is a static one; None for StableHLO payloads."""
        return self._program

    def state_dict(self):
        if self._loaded is not None:
            return self._loaded.state_dict()
        return dict(self._program._consts)


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle.inference.create_predictor."""
    return Predictor(config)


class DataType:
    """Reference: paddle_infer.DataType enum."""

    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    """Reference: paddle_infer.PlaceType enum (TPU fills the GPU role)."""

    CPU = "cpu"
    GPU = "tpu"
    XPU = "xpu"
    CUSTOM = "custom"


class PrecisionType:
    """Reference: paddle_infer.PrecisionType (TRT precision selector)."""

    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


def get_version() -> str:
    """Reference: paddle_infer.get_version."""
    import paddle_tpu

    return f"paddle_tpu inference {getattr(paddle_tpu, '__version__', '0.0')}"


def get_num_bytes_of_data_type(dtype) -> int:
    import numpy as np

    return int(np.dtype(getattr(dtype, "value", dtype)).itemsize)


def get_trt_compile_version():
    """TensorRT is not part of the TPU build (XLA compiles the graph)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    """Reference maps fluid op names to phi kernel names; the TPU build's
    primitives already use the phi-style names."""
    return op_name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Cast a saved state_dict payload to a mixed-precision copy
    (reference: paddle.inference.convert_to_mixed_precision)."""
    import numpy as np

    from .framework.io_ import load, save

    state = load(model_file if params_file is None else params_file)
    dt = getattr(mixed_precision, "value", mixed_precision) or "float16"
    out = {}
    for k, v in state.items():
        arr = np.asarray(v.numpy() if hasattr(v, "numpy") else v)
        out[k] = arr.astype(dt) if np.issubdtype(arr.dtype, np.floating) \
            else arr
    save(out, mixed_params_file or mixed_model_file)


class PredictorPool:
    """Pool of predictors sharing one config (reference:
    paddle_infer.PredictorPool for multi-threaded serving)."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [Predictor(config) for _ in range(max(1, size))]

    def retrieve(self, idx: int) -> Predictor:
        return self._predictors[idx]


__all__ += ["DataType", "PlaceType", "PrecisionType", "get_version",
            "get_num_bytes_of_data_type", "get_trt_compile_version",
            "get_trt_runtime_version", "convert_to_mixed_precision",
            "PredictorPool", "_get_phi_kernel_name"]


class XpuConfig:
    """Reference: paddle_infer.XpuConfig — XPU runtime knobs. Accepted for
    config portability; XPU execution is not part of the TPU build."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.conv_autotune_level = 0
