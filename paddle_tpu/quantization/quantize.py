"""Quantization base + model surgery (reference:
python/paddle/quantization/quantize.py, qat.py:23, ptq.py:24)."""
from __future__ import annotations

import copy

from ..nn.layer import Layer
from .config import QuantConfig
from .wrapper import ObserveWrapper, QuantedWrapper


def _replace_layers(model, config, wrapper_cls, prefix=""):
    for name, child in list(model.named_children()):
        full = f"{prefix}.{name}" if prefix else name
        cfg = config._config_for(child, full) or config.global_config
        is_leaf = not any(True for _ in child.named_children()) or type(
            child
        ) in config.customized_leaves
        quantizable = cfg is not None and (
            hasattr(child, "weight") or cfg.activation is not None
        )
        if quantizable and is_leaf:
            mapped = config.qat_layer_mappings.get(type(child))
            wrapped = (
                mapped(child, cfg) if mapped is not None else wrapper_cls(child, cfg)
            )
            model.add_sublayer(name, wrapped)
        else:
            _replace_layers(child, config, wrapper_cls, full)
    return model


class Quantization:
    def __init__(self, config):
        if not isinstance(config, QuantConfig):
            raise TypeError("config should be a QuantConfig instance")
        self._config = config

    def quantize(self, model, inplace=False):
        raise NotImplementedError

    def convert(self, model, inplace=False):
        """Replace QAT/PTQ wrappers with plain layers whose weights are
        baked onto the quantized grid (reference quantize.py convert)."""
        target = model if inplace else copy.deepcopy(model)
        self._convert_inner(target)
        return target

    def _convert_inner(self, model):
        for name, child in list(model.named_children()):
            if isinstance(child, (QuantedWrapper, ObserveWrapper)):
                model.add_sublayer(name, child.converted_layer())
            else:
                self._convert_inner(child)


class QAT(Quantization):
    """Quantization-aware training (reference qat.py:23)."""

    def quantize(self, model, inplace=False):
        self._config._materialize_names(model)
        target = model if inplace else copy.deepcopy(model)
        _replace_layers(target, self._config, QuantedWrapper)
        return target


class PTQ(Quantization):
    """Post-training quantization (reference ptq.py:24)."""

    def quantize(self, model, inplace=False):
        self._config._materialize_names(model)
        target = model if inplace else copy.deepcopy(model)
        target.eval()
        _replace_layers(target, self._config, ObserveWrapper)
        return target
