"""Quantization base classes and the fake-quant primitive.

Reference: python/paddle/quantization/base_quanter.py:25,
base_observer.py:21, factory.py:52-130. Fake quantization runs as one
framework primitive with a straight-through-estimator VJP (gradient passes
inside the clip range, zero outside) — the TPU analog of the reference's
fake_quantize_dequantize_moving_average_abs_max kernel pair.
"""
from __future__ import annotations

import abc

import jax.numpy as jnp

from ..core.dispatch import register_primitive
from ..core.tensor import apply
from ..nn.layer import Layer


def _fake_quant_fwd(x, scale, *, bit_length, quant_axis):
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    if quant_axis is not None and s.ndim:
        shape = [1] * x.ndim
        shape[quant_axis] = -1
        s = s.reshape(shape)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) / qmax * s


def _fake_quant_vjp(grads_out, saved, *, bit_length, quant_axis):
    x, scale = saved
    s = jnp.maximum(scale, 1e-9)
    if quant_axis is not None and s.ndim:
        shape = [1] * x.ndim
        shape[quant_axis] = -1
        s = s.reshape(shape)
    g = grads_out[0]
    mask = (jnp.abs(x) <= s).astype(g.dtype)
    return (g * mask, None)


register_primitive(
    "fake_quant_dequant", _fake_quant_fwd, vjp=_fake_quant_vjp
)


def fake_quant_dequant(x, scale, bit_length=8, quant_axis=None):
    return apply(
        "fake_quant_dequant", x, scale,
        bit_length=int(bit_length), quant_axis=quant_axis,
    )


class BaseQuanter(Layer, metaclass=abc.ABCMeta):
    """Built-in and customized quanters implement forward + quant params."""

    @abc.abstractmethod
    def forward(self, input):
        ...

    @abc.abstractmethod
    def scales(self):
        ...

    @abc.abstractmethod
    def zero_points(self):
        ...

    def quant_axis(self):
        return None

    def bit_length(self):
        return 8


class BaseObserver(BaseQuanter, metaclass=abc.ABCMeta):
    """Observers collect statistics during calibration (PTQ)."""

    @abc.abstractmethod
    def cal_thresholds(self):
        ...


class ClassWithArguments(metaclass=abc.ABCMeta):
    def __init__(self, **kwargs):
        self._args = kwargs

    @property
    def args(self):
        return self._args

    @abc.abstractmethod
    def _get_class(self):
        ...

    def _instance(self, layer):
        return self._get_class()(layer, **self._args)


class QuanterFactory(ClassWithArguments):
    """Holds a quanter class + ctor args (reference factory.py:52)."""

    def __init__(self, cls=None, **kwargs):
        super().__init__(**kwargs)
        self._cls = cls

    def _get_class(self):
        return self._cls


ObserverFactory = QuanterFactory


def quanter(class_name):
    """Decorator declaring a factory class for a quanter
    (reference factory.py:78): adds ``class_name`` to the quanter's module
    so users write ``MyQuanter(bit_length=8)`` to get a factory."""

    def wrapper(cls):
        import sys

        def fac_init(self, **kwargs):
            QuanterFactory.__init__(self, cls, **kwargs)

        fac = type(class_name, (QuanterFactory,), {"__init__": fac_init})
        setattr(sys.modules[cls.__module__], class_name, fac)
        cls.__factory__ = fac
        return cls

    return wrapper
