"""Quantized layer wrappers (reference: python/paddle/quantization/wrapper.py
+ paddle/nn/quant/qat behavior): wrap a layer with activation/weight
quanters; convert() bakes weights onto the quantized grid."""
from __future__ import annotations

from ..nn.layer import Layer
from .base import BaseQuanter, fake_quant_dequant


class QuantedWrapper(Layer):
    """Generic QAT wrapper: input → activation_quanter, weight →
    weight_quanter, then the wrapped layer's functional forward."""

    def __init__(self, layer, q_config_entry):
        super().__init__()
        self._layer = layer
        self.activation_quanter = (
            q_config_entry.activation._instance(layer)
            if q_config_entry.activation is not None
            else None
        )
        self.weight_quanter = (
            q_config_entry.weight._instance(layer)
            if q_config_entry.weight is not None
            else None
        )

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._layer, "weight"):
            w = self._layer.weight
            qw = self.weight_quanter(w)
            # run the wrapped layer with the fake-quantized weight
            orig = w
            try:
                self._layer.weight = qw
                return self._layer(x)
            finally:
                self._layer.weight = orig
        return self._layer(x)

    def converted_layer(self):
        """Bake fake-quantized weights into the wrapped layer and return it
        (reference Quantization.convert semantics)."""
        if self.weight_quanter is not None and hasattr(self._layer, "weight"):
            qw = self.weight_quanter(self._layer.weight)
            self._layer.weight._replace_value(qw._value)
        return self._layer


class ObserveWrapper(Layer):
    """PTQ wrapper: observers watch activations/weights without altering
    the computation (reference wrapper.py ObserveWrapper)."""

    def __init__(self, layer, q_config_entry):
        super().__init__()
        self._layer = layer
        self.activation_observer = (
            q_config_entry.activation._instance(layer)
            if q_config_entry.activation is not None
            else None
        )
        self.weight_observer = (
            q_config_entry.weight._instance(layer)
            if q_config_entry.weight is not None
            else None
        )

    def forward(self, x):
        if self.activation_observer is not None:
            x = self.activation_observer(x)
        if self.weight_observer is not None and hasattr(self._layer, "weight"):
            self.weight_observer(self._layer.weight)
        return self._layer(x)

    def converted_layer(self):
        if self.weight_observer is not None and hasattr(self._layer, "weight"):
            scale = self.weight_observer.scales()
            if scale is not None:
                qw = fake_quant_dequant(
                    self._layer.weight, scale, self.weight_observer.bit_length()
                )
                self._layer.weight._replace_value(qw._value)
        return self._layer
