"""Quantized layer wrappers (reference: python/paddle/quantization/wrapper.py
+ paddle/nn/quant/qat behavior): wrap a layer with activation/weight
quanters; convert() bakes weights onto the quantized grid."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .base import fake_quant_dequant


def _expanded_scale(scale, weight):
    """Broadcast a per-group scale (n_groups, *rest) onto the weight's rows
    so the fake-quant grid covers each group (GroupWiseWeightObserver)."""
    sv = np.asarray(scale._value)
    if sv.ndim and sv.shape[0] not in (1, weight.shape[0]):
        g = -(-weight.shape[0] // sv.shape[0])  # rows per group (ceil)
        sv = np.repeat(sv, g, axis=0)[: weight.shape[0]]
        return Tensor._from_value(sv)
    return scale


def _bake_weight(layer, quanter):
    """Quantize-dequantize the stored weight with the quanter's CURRENT
    scales — never through quanter.forward, which would mutate the
    moving-average state during convert."""
    scale = quanter.scales()
    if scale is None or not hasattr(layer, "weight"):
        return
    scale = _expanded_scale(scale, layer.weight)
    qw = fake_quant_dequant(layer.weight, scale, quanter.bit_length())
    layer.weight._replace_value(qw._value)


class QuantedWrapper(Layer):
    """Generic QAT wrapper: input → activation_quanter, weight →
    weight_quanter, then the wrapped layer's functional forward."""

    def __init__(self, layer, q_config_entry):
        super().__init__()
        self._layer = layer
        self.activation_quanter = (
            q_config_entry.activation._instance(layer)
            if q_config_entry.activation is not None
            else None
        )
        self.weight_quanter = (
            q_config_entry.weight._instance(layer)
            if q_config_entry.weight is not None and hasattr(layer, "weight")
            else None
        )

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._layer, "weight"):
            w = self._layer.weight
            qw = self.weight_quanter(w)
            # run the wrapped layer with the fake-quantized weight
            orig = w
            try:
                self._layer.weight = qw
                return self._layer(x)
            finally:
                self._layer.weight = orig
        return self._layer(x)

    def converted_layer(self):
        """Bake fake-quantized weights into the wrapped layer and return it
        (reference Quantization.convert semantics)."""
        if self.weight_quanter is not None:
            _bake_weight(self._layer, self.weight_quanter)
        return self._layer


class ObserveWrapper(Layer):
    """PTQ wrapper: observers watch activations/weights without altering
    the computation (reference wrapper.py ObserveWrapper)."""

    def __init__(self, layer, q_config_entry):
        super().__init__()
        self._layer = layer
        self.activation_observer = (
            q_config_entry.activation._instance(layer)
            if q_config_entry.activation is not None
            else None
        )
        self.weight_observer = (
            q_config_entry.weight._instance(layer)
            if q_config_entry.weight is not None and hasattr(layer, "weight")
            else None
        )

    def forward(self, x):
        if self.activation_observer is not None:
            x = self.activation_observer(x)
        if self.weight_observer is not None:
            self.weight_observer(self._layer.weight)
        return self._layer(x)

    def converted_layer(self):
        if self.weight_observer is not None:
            _bake_weight(self._layer, self.weight_observer)
        return self._layer
