"""PTQ observers (reference: python/paddle/quantization/observers/abs_max.py,
groupwise.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .base import BaseObserver, fake_quant_dequant, quanter


@quanter("AbsmaxObserver")
class AbsmaxObserverLayer(BaseObserver):
    """Per-tensor abs-max calibration (reference observers/abs_max.py)."""

    def __init__(self, layer=None, quant_bits=8, dtype="float32", name=None):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self.register_buffer(
            "abs_max_val", Tensor._from_value(jnp.asarray(1e-9, np.dtype(dtype)))
        )

    def forward(self, input):
        absmax = jnp.maximum(jnp.max(jnp.abs(input._value)), self.abs_max_val._value)
        self.abs_max_val._replace_value(absmax.astype(self.abs_max_val._value.dtype))
        return input

    def cal_thresholds(self):
        return self.abs_max_val

    def scales(self):
        return self.abs_max_val

    def zero_points(self):
        return None

    def bit_length(self):
        return self._quant_bits


@quanter("GroupWiseWeightObserver")
class GroupWiseWeightObserverLayer(BaseObserver):
    """Group-wise abs-max for weights (reference observers/groupwise.py):
    scales per group of ``group_size`` rows along axis 0."""

    def __init__(self, layer=None, quant_bits=4, group_size=128, dtype="float32",
                 name=None):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self._group_size = int(group_size)
        self._scale = None

    def forward(self, input):
        x = input._value
        n = x.shape[0]
        g = min(self._group_size, n)
        pad = (-n) % g
        xp = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
        grouped = xp.reshape((xp.shape[0] // g, g) + xp.shape[1:])
        scale = jnp.max(jnp.abs(grouped), axis=1)
        self._scale = Tensor._from_value(scale)
        return input

    def cal_thresholds(self):
        return self._scale

    def scales(self):
        return self._scale

    def zero_points(self):
        return None

    def bit_length(self):
        return self._quant_bits
