"""Quantization configuration (reference: python/paddle/quantization/config.py:35-440).

QuantConfig maps layers (by instance, by type, or by name prefix) to a
SingleLayerConfig of (activation quanter factory, weight quanter factory)."""
from __future__ import annotations

from ..nn.layer import Layer
from .base import QuanterFactory


class SingleLayerConfig:
    def __init__(self, activation, weight):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    def __init__(self, activation, weight):
        if activation is not None and not isinstance(activation, QuanterFactory):
            raise TypeError("activation should be a QuanterFactory or None")
        if weight is not None and not isinstance(weight, QuanterFactory):
            raise TypeError("weight should be a QuanterFactory or None")
        self._global_config = (
            SingleLayerConfig(activation, weight)
            if activation is not None or weight is not None
            else None
        )
        self._layer_configs = {}      # id(layer) -> SingleLayerConfig
        self._type_configs = {}       # type -> SingleLayerConfig
        self._prefix_configs = {}     # name prefix -> SingleLayerConfig
        # instance configs pinned to full names before deepcopy — checked
        # FIRST so they keep instance priority over name configs
        self._pinned_instance_configs = {}
        self._qat_layer_mapping = {}  # source type -> quanted type
        self._customized_leaves = []

    @property
    def global_config(self):
        return self._global_config

    def add_layer_config(self, layer, activation=None, weight=None):
        """Highest-priority per-instance config (reference config.py:105)."""
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            if not isinstance(l, Layer):
                raise TypeError("layer should be a paddle Layer instance")
            self._layer_configs[id(l)] = SingleLayerConfig(activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        """Config by layer full name (reference config.py:154)."""
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._prefix_configs[str(n)] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        """Config by layer type (reference config.py:204)."""
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            if not (isinstance(t, type) and issubclass(t, Layer)):
                raise TypeError("layer_type should be a Layer subclass")
            self._type_configs[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source, target):
        """Map a layer type to a customized quantized implementation
        (reference config.py:253)."""
        if not (isinstance(source, type) and issubclass(source, Layer)):
            raise TypeError("The source layer should be a subclass of Layer")
        self._qat_layer_mapping[source] = target

    def add_customized_leaf(self, layer_type):
        self._customized_leaves.append(layer_type)

    @property
    def qat_layer_mappings(self):
        return self._qat_layer_mapping

    @property
    def customized_leaves(self):
        return self._customized_leaves

    def _materialize_names(self, model):
        """Pin id-keyed per-instance configs to layer full names BEFORE the
        model is deep-copied for out-of-place quantize — the copy has new
        object ids, so id-keyed lookups would silently miss."""
        if not self._layer_configs:
            return

        def walk(layer, prefix=""):
            for name, child in layer.named_children():
                full = f"{prefix}.{name}" if prefix else name
                cfg = self._layer_configs.get(id(child))
                if cfg is not None:
                    self._pinned_instance_configs[full] = cfg
                walk(child, full)

        walk(model)

    def _config_for(self, layer, full_name=""):
        """Resolve the effective config for one layer: instance > name >
        type > global (reference priority order)."""
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        if full_name in self._pinned_instance_configs:
            return self._pinned_instance_configs[full_name]
        for prefix, cfg in self._prefix_configs.items():
            if full_name == prefix or full_name.startswith(prefix + "."):
                return cfg
        if type(layer) in self._type_configs:
            return self._type_configs[type(layer)]
        return None

    def _need_quant(self, layer, full_name=""):
        return self._config_for(layer, full_name) is not None
