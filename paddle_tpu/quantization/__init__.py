"""``paddle.quantization`` parity package (reference:
python/paddle/quantization/__init__.py)."""
from .base import (
    BaseObserver,
    BaseQuanter,
    ObserverFactory,
    QuanterFactory,
    fake_quant_dequant,
    quanter,
)
from .config import QuantConfig, SingleLayerConfig
from .observers import (
    AbsmaxObserver,
    AbsmaxObserverLayer,
    GroupWiseWeightObserver,
    GroupWiseWeightObserverLayer,
)
from .quanters import (
    FakeQuanterWithAbsMaxObserver,
    FakeQuanterWithAbsMaxObserverLayer,
)
from .quantize import PTQ, QAT, Quantization
from .wrapper import ObserveWrapper, QuantedWrapper

__all__ = [
    "QuantConfig", "SingleLayerConfig", "BaseQuanter", "BaseObserver",
    "QuanterFactory", "ObserverFactory", "quanter",
    "FakeQuanterWithAbsMaxObserver", "AbsmaxObserver",
    "GroupWiseWeightObserver", "QAT", "PTQ", "Quantization",
    "QuantedWrapper", "ObserveWrapper", "fake_quant_dequant",
]
