"""QAT quanters (reference: python/paddle/quantization/quanters/abs_max.py).

FakeQuanterWithAbsMaxObserver: moving-average abs-max scale, fake
quant-dequant with straight-through gradients while training."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .base import BaseQuanter, fake_quant_dequant, quanter


@quanter("FakeQuanterWithAbsMaxObserver")
class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    def __init__(self, layer=None, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self._moving_rate = float(moving_rate)
        self._bit_length = int(bit_length)
        self.register_buffer(
            "scale", Tensor._from_value(jnp.asarray(1.0, np.dtype(dtype)))
        )
        self.register_buffer(
            "state", Tensor._from_value(jnp.asarray(0.0, np.dtype(dtype)))
        )

    def forward(self, input):
        if self.training:
            absmax = jnp.max(jnp.abs(input._value))
            state = self.state._value + 1.0
            # moving-average absmax (reference abs_max.py accum semantics)
            accum = self._moving_rate * self.scale._value * jnp.minimum(
                self.state._value, 1.0
            ) + absmax * (1.0 - self._moving_rate * jnp.minimum(self.state._value, 1.0))
            self.state._replace_value(state)
            self.scale._replace_value(accum.astype(self.scale._value.dtype))
        return fake_quant_dequant(input, self.scale, self._bit_length)

    def scales(self):
        return self.scale

    def zero_points(self):
        return None

    def bit_length(self):
        return self._bit_length
