"""Graph reindexing (reference: python/paddle/geometric/reindex.py:25,139).

Host-side graph preprocessing with data-dependent output shapes — in the
reference these are CPU/GPU kernels used between sampling steps; here they
run eagerly in numpy (the results feed static-shape device programs).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor

__all__ = ["reindex_graph", "reindex_heter_graph"]


def _np(t):
    return np.asarray(ensure_tensor(t)._value)


def _reindex(x, neighbor_list, count_list):
    """Shared core: map original ids to [0, num_unique) with center nodes
    first (reference semantics: out_nodes = x ++ first-seen neighbors)."""
    out_nodes = list(x.tolist())
    mapping = {int(v): i for i, v in enumerate(out_nodes)}
    all_neighbors = np.concatenate(neighbor_list) if neighbor_list else np.empty(0, x.dtype)
    for v in all_neighbors.tolist():
        if int(v) not in mapping:
            mapping[int(v)] = len(out_nodes)
            out_nodes.append(int(v))
    reindex_src = np.asarray([mapping[int(v)] for v in all_neighbors], dtype=x.dtype)
    # dst: center node of each neighbor, repeated per count
    dst_all = []
    for neighbors, count in zip(neighbor_list, count_list):
        dst = np.repeat(np.arange(len(x), dtype=x.dtype), count)
        dst_all.append(dst)
    reindex_dst = np.concatenate(dst_all) if dst_all else np.empty(0, x.dtype)
    return (
        np.asarray(reindex_src),
        reindex_dst,
        np.asarray(out_nodes, dtype=x.dtype),
    )


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    x_np, nbr, cnt = _np(x), _np(neighbors), _np(count)
    src, dst, nodes = _reindex(x_np, [nbr], [cnt])
    return (
        Tensor._from_value(src),
        Tensor._from_value(dst),
        Tensor._from_value(nodes),
    )


def reindex_heter_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                        name=None):
    x_np = _np(x)
    nbrs = [_np(n) for n in neighbors]
    cnts = [_np(c) for c in count]
    src, dst, nodes = _reindex(x_np, nbrs, cnts)
    return (
        Tensor._from_value(src),
        Tensor._from_value(dst),
        Tensor._from_value(nodes),
    )
