"""Segment reductions (reference: python/paddle/geometric/math.py:23-260).

Lowered to XLA's segment reductions (jax.ops.segment_*), which compile to
efficient TPU scatter programs. ``num_segments`` is shape-determining, so the
wrapper reads the last segment id eagerly (paddle semantics: segment_ids are
sorted, result has segment_ids[-1]+1 rows) and passes it as a static arg."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..ops._helpers import defprim, ensure_tensor

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max"]

def _segment_counts(ids, n):
    return jax.ops.segment_sum(jnp.ones_like(ids, jnp.int32), ids, num_segments=n)


def _bshape(n, data):
    return (n,) + (1,) * (data.ndim - 1)


def _segment_mean_raw(data, ids, n):
    s = jax.ops.segment_sum(data, ids, num_segments=n)
    c = jnp.maximum(_segment_counts(ids, n), 1).reshape(_bshape(n, data))
    return (s / c.astype(s.dtype)).astype(data.dtype)


def _segment_extreme_raw(data, ids, n, op):
    """segment min/max with paddle's empty-segment fill of 0 — masked on the
    segment count, so integer sentinels and legitimate ±inf values survive."""
    fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    m = fn(data, ids, num_segments=n)
    empty = (_segment_counts(ids, n) == 0).reshape(_bshape(n, data))
    return jnp.where(empty, jnp.zeros((), data.dtype), m)


defprim(
    "segment_sum_p",
    lambda data, ids, *, n: jax.ops.segment_sum(data, ids, num_segments=n),
)
defprim("segment_mean_p", lambda data, ids, *, n: _segment_mean_raw(data, ids, n))
defprim(
    "segment_min_p", lambda data, ids, *, n: _segment_extreme_raw(data, ids, n, "min")
)
defprim(
    "segment_max_p", lambda data, ids, *, n: _segment_extreme_raw(data, ids, n, "max")
)


def _segment(prim, data, segment_ids):
    data, segment_ids = ensure_tensor(data), ensure_tensor(segment_ids)
    if segment_ids.ndim != 1:
        raise ValueError("segment_ids should be 1-D")
    n = int(np.asarray(segment_ids._value[-1])) + 1 if segment_ids.shape[0] else 0
    return apply(prim, data, segment_ids, n=n)


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum_p", data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    return _segment("segment_mean_p", data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min_p", data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max_p", data, segment_ids)
