"""``paddle.geometric`` parity package (reference: python/paddle/geometric/__init__.py:20-32)."""
from .math import segment_max, segment_mean, segment_min, segment_sum
from .message_passing import send_u_recv, send_ue_recv, send_uv
from .reindex import reindex_graph, reindex_heter_graph
from .sampling import sample_neighbors, weighted_sample_neighbors

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]
