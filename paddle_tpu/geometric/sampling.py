"""Neighbor sampling (reference: python/paddle/geometric/sampling/neighbors.py:23,172).

Graph stored CSC: ``row`` holds the source of every edge, ``colptr[i]:
colptr[i+1]`` spans the in-edges of node i. Data-dependent output shapes →
host-side numpy, seeded from the framework generator stream so paddle.seed
reproduces draws."""
from __future__ import annotations

import numpy as np

from ..core import generator
from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor

__all__ = ["sample_neighbors", "weighted_sample_neighbors"]


def _np(t):
    return np.asarray(ensure_tensor(t)._value)


def _rng():
    import jax

    key = generator.next_key()
    return np.random.default_rng(int(jax.random.randint(key, (), 0, 2**31 - 1)))


def _sample(row, colptr, nodes, sample_size, eids, return_eids, weights=None):
    rng = _rng()
    out_neighbors, out_counts, out_eids = [], [], []
    for node in nodes.tolist():
        beg, end = int(colptr[node]), int(colptr[node + 1])
        cand = row[beg:end]
        idx = np.arange(beg, end)
        if sample_size != -1 and len(cand) > sample_size:
            if weights is not None:
                w = weights[beg:end].astype("float64")
                p = w / w.sum()
                pick = rng.choice(len(cand), size=sample_size, replace=False, p=p)
            else:
                pick = rng.choice(len(cand), size=sample_size, replace=False)
            cand, idx = cand[pick], idx[pick]
        out_neighbors.append(cand)
        out_counts.append(len(cand))
        out_eids.append(eids[idx] if eids is not None else idx)
    neighbors = np.concatenate(out_neighbors) if out_neighbors else np.empty(0, row.dtype)
    counts = np.asarray(out_counts, dtype=row.dtype)
    rets = (Tensor._from_value(neighbors), Tensor._from_value(counts))
    if return_eids:
        e = np.concatenate(out_eids) if out_eids else np.empty(0, row.dtype)
        rets = rets + (Tensor._from_value(e.astype(row.dtype)),)
    return rets


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    if return_eids and eids is None:
        raise ValueError("eids should not be None if return_eids is True.")
    return _sample(
        _np(row), _np(colptr), _np(input_nodes), int(sample_size),
        None if eids is None else _np(eids), return_eids,
    )


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes, sample_size=-1,
                              eids=None, return_eids=False, name=None):
    if return_eids and eids is None:
        raise ValueError("eids should not be None if return_eids is True.")
    return _sample(
        _np(row), _np(colptr), _np(input_nodes), int(sample_size),
        None if eids is None else _np(eids), return_eids, weights=_np(edge_weight),
    )
