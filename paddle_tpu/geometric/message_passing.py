"""Graph message passing (reference:
python/paddle/geometric/message_passing/send_recv.py:36,186,389).

send_u_recv gathers source-node features along edges and scatter-reduces to
destinations — one fused XLA gather+segment-reduce program on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..ops._helpers import binary_args, defprim, ensure_tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv"]

_MSG_OPS = ("add", "sub", "mul", "div")
_REDUCE_OPS = ("sum", "mean", "max", "min")


from .math import _segment_extreme_raw, _segment_mean_raw


def _message(x_e, y_e, op):
    if op == "add":
        return x_e + y_e
    if op == "sub":
        return x_e - y_e
    if op == "mul":
        return x_e * y_e
    return x_e / y_e


def _reduce(msg, dst, n, op):
    if op == "sum":
        return jax.ops.segment_sum(msg, dst, num_segments=n)
    if op == "mean":
        return _segment_mean_raw(msg, dst, n)
    return _segment_extreme_raw(msg, dst, n, op)


defprim(
    "send_u_recv_p",
    lambda x, src, dst, *, reduce_op, n: _reduce(x[src], dst, n, reduce_op),
)
def _send_ue_recv_fwd(x, y, src, dst, *, message_op, reduce_op, n):
    x_e = x[src]
    # edge features broadcast against node features on trailing dims
    if y.ndim < x_e.ndim:
        y = y.reshape(y.shape + (1,) * (x_e.ndim - y.ndim))
    return _reduce(_message(x_e, y, message_op), dst, n, reduce_op)


defprim("send_ue_recv_p", _send_ue_recv_fwd)
defprim(
    "send_uv_p",
    lambda x, y, src, dst, *, message_op: _message(x[src], y[dst], message_op),
)


def _indices(src_index, dst_index):
    src, dst = ensure_tensor(src_index), ensure_tensor(dst_index)
    if src.ndim != 1 or dst.ndim != 1 or src.shape[0] != dst.shape[0]:
        raise ValueError("src_index and dst_index should be 1-D with equal length")
    return src, dst


def _out_size(out_size, dst):
    if out_size is not None:
        return int(out_size)
    return int(np.asarray(jnp.max(dst._value))) + 1 if dst.shape[0] else 0


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None, name=None):
    if reduce_op not in _REDUCE_OPS:
        raise ValueError(f"reduce_op should be one of {_REDUCE_OPS}, got {reduce_op}")
    x = ensure_tensor(x)
    src, dst = _indices(src_index, dst_index)
    return apply("send_u_recv_p", x, src, dst, reduce_op=reduce_op,
                 n=_out_size(out_size, dst))


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    if message_op not in _MSG_OPS:
        raise ValueError(f"message_op should be one of {_MSG_OPS}, got {message_op}")
    if reduce_op not in _REDUCE_OPS:
        raise ValueError(f"reduce_op should be one of {_REDUCE_OPS}, got {reduce_op}")
    x, y = binary_args(x, y)
    src, dst = _indices(src_index, dst_index)
    return apply("send_ue_recv_p", x, y, src, dst, message_op=message_op,
                 reduce_op=reduce_op, n=_out_size(out_size, dst))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    if message_op not in _MSG_OPS:
        raise ValueError(f"message_op should be one of {_MSG_OPS}, got {message_op}")
    x, y = binary_args(x, y)
    src, dst = _indices(src_index, dst_index)
    return apply("send_uv_p", x, y, src, dst, message_op=message_op)
