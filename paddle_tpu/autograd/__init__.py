"""Autograd public API.

Reference surface: python/paddle/autograd/ — backward, grad (GeneralGrad,
fluid/eager/general_grad.h), PyLayer (autograd/py_layer.py), no_grad.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from .engine import (
    enable_grad,
    grad_enabled,
    no_grad,
    run_backward,
    run_backward_create_graph,
    set_grad_enabled,
)
from .functional import Hessian, Jacobian, hessian, jacobian
from .py_layer import PyLayer, PyLayerContext

__all__ = [
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "PyLayer",
    "PyLayerContext",
    "jacobian",
    "hessian",
    "Jacobian",
    "Hessian",
]


def is_grad_enabled():
    return grad_enabled()


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    only_inputs: bool = True,
    allow_unused: bool = False,
    no_grad_vars=None,
):
    """paddle.grad parity (reference: python/paddle/autograd/autograd.py,
    C++ GeneralGrad partial-graph engine). Computes grads of ``outputs``
    w.r.t. ``inputs`` without touching ``.grad`` fields.

    With create_graph=True the backward pass replays through the primitive
    layer, so the returned grads carry their own grad graph — paddle.grad
    composes to arbitrary derivative order (double backward and beyond).
    """
    from ..core.tensor import Tensor

    single = isinstance(inputs, Tensor)
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if single else list(inputs)
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    capture = {}
    for i, t in enumerate(inputs):
        if t._node is not None:
            capture[(id(t._node), t._out_slot)] = i
        else:
            capture[(id(t._accum_node()), 0)] = i

    if create_graph:
        # create_graph implies the graph survives (reference semantics:
        # retain_graph defaults to create_graph)
        retain = bool(retain_graph) if retain_graph is not None else True
        captured = run_backward_create_graph(
            outputs, grad_outputs, capture=capture, retain_graph=retain
        )
    else:
        retain = bool(retain_graph) if retain_graph is not None else False
        captured = run_backward(
            outputs,
            grad_outputs,
            retain_graph=retain,
            capture=capture,
            accumulate_leaves=False,
        )
    result = []
    for i, t in enumerate(inputs):
        g = captured.get(i)
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {i} is unreachable from outputs; pass "
                    "allow_unused=True to return None for it"
                )
            result.append(None)
        elif isinstance(g, Tensor):
            result.append(g)
        else:
            result.append(Tensor._from_value(g))
    return result


class saved_tensors_hooks:
    """Reference: autograd/saved_tensors_hooks.py — pack/unpack hooks
    applied to tensors the tape saves for backward (e.g. offload-to-host
    compression). Hooks wrap GradNode saved tensors while the context is
    active."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import engine

        engine._saved_tensor_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from . import engine

        engine._saved_tensor_hooks.pop()
        return False


__all__.append("saved_tensors_hooks")
