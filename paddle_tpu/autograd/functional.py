"""Functional differentiation API — paddle.autograd.jacobian / hessian.

Reference: python/paddle/autograd/autograd.py (Jacobian :30, Hessian :183,
jacobian :450, hessian :544). Rows are computed through the eager engine
with basis-vector seeds; hessian composes jacobian over
``paddle.grad(..., create_graph=True)`` (true double backward, not finite
differences)."""
from __future__ import annotations

import numpy as np

__all__ = ["jacobian", "hessian", "Jacobian", "Hessian"]


def _as_list(x):
    from ..core.tensor import Tensor

    return ([x], True) if isinstance(x, Tensor) else (list(x), False)


class Jacobian:
    """Materialized Jacobian with paddle's shape contract:
    batch_axis=None → (M, N); batch_axis=0 → (B, M, N)."""

    def __init__(self, tensor):
        self._t = tensor

    def __getitem__(self, idx):
        return self._t[idx]

    @property
    def shape(self):
        return self._t.shape

    def numpy(self):
        return np.asarray(self._t._value)

    @property
    def tensor(self):
        return self._t


class Hessian(Jacobian):
    pass


def _flat_len(shape, batch_axis):
    n = 1
    for i, s in enumerate(shape):
        if batch_axis is not None and i == batch_axis:
            continue
        n *= s
    return n


def _jacobian_single(y, x, batch_axis, create_graph=False):
    """J of one output tensor w.r.t. one input tensor."""
    import jax.numpy as jnp

    from . import grad as grad_fn
    from ..core.tensor import Tensor
    from ..ops.manipulation import reshape, stack

    m = _flat_len(tuple(y.shape), batch_axis)
    n = _flat_len(tuple(x.shape), batch_axis)
    if y.stop_gradient and y._node is None:
        # constant output (e.g. zero grad of an unused input): J is zeros
        shape = (m, n) if batch_axis is None else (y.shape[0], m, n)
        return Tensor._from_value(jnp.zeros(shape, x.dtype))
    if batch_axis is None:
        rows = []
        for j in range(m):
            seed = np.zeros(max(m, 1), "float32")
            seed[j] = 1.0
            seed_t = Tensor._from_value(
                jnp.asarray(seed.reshape(tuple(y.shape)), dtype=y.dtype)
            )
            (g,) = grad_fn([y], [x], grad_outputs=[seed_t], retain_graph=True,
                           create_graph=create_graph, allow_unused=True)
            if g is None:
                g = Tensor._from_value(jnp.zeros(tuple(x.shape), x.dtype))
            rows.append(reshape(g, [n]))
        return stack(rows, 0)                          # (M, N)
    if batch_axis != 0:
        raise ValueError("batch_axis must be None or 0")
    b = y.shape[0]
    rows = []
    for j in range(m):
        seed = np.zeros((b, m), "float32")
        seed[:, j] = 1.0
        seed_t = Tensor._from_value(
            jnp.asarray(seed.reshape((b,) + tuple(y.shape[1:])), dtype=y.dtype)
        )
        (g,) = grad_fn([y], [x], grad_outputs=[seed_t], retain_graph=True,
                       create_graph=create_graph, allow_unused=True)
        if g is None:
            g = Tensor._from_value(jnp.zeros(tuple(x.shape), x.dtype))
        rows.append(reshape(g, [b, n]))
    return stack(rows, 1)                              # (B, M, N)


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian parity: Jacobian of output tensors w.r.t.
    input tensors, evaluated through the autograd engine."""
    ys_list, y_single = _as_list(ys)
    xs_list, x_single = _as_list(xs)
    rows = [
        [Jacobian(_jacobian_single(y, x, batch_axis)) for x in xs_list]
        for y in ys_list
    ]
    if y_single and x_single:
        return rows[0][0]
    if y_single:
        return tuple(rows[0])
    if x_single:
        return tuple(r[0] for r in rows)
    return tuple(tuple(r) for r in rows)


def hessian(ys, xs, batch_axis=None):
    """paddle.autograd.hessian parity: ys must be scalar (or per-batch
    scalar); H[i][j] = ∂²y / ∂x_i ∂x_j via double backward."""
    from . import grad as grad_fn

    ys_list, _ = _as_list(ys)
    if len(ys_list) != 1:
        raise ValueError("hessian expects a single scalar output")
    y = ys_list[0]
    scalar_elems = _flat_len(tuple(y.shape), batch_axis)
    if scalar_elems != 1:
        raise ValueError(
            f"hessian expects ys to be a scalar per batch, got shape {y.shape}"
        )
    xs_list, x_single = _as_list(xs)
    grads = grad_fn([y], xs_list, create_graph=True, retain_graph=True,
                    allow_unused=True)
    out = []
    for gi, xi in zip(grads, xs_list):
        if gi is None:
            # input unused by ys → zero gradient with a well-defined shape,
            # so its Hessian blocks come out as zeros
            import jax.numpy as jnp

            from ..core.tensor import Tensor

            gi = Tensor._from_value(jnp.zeros(tuple(xi.shape), xi.dtype))
        row = [Hessian(_jacobian_single(gi, x, batch_axis)) for x in xs_list]
        out.append(row)
    if x_single:
        return out[0][0]
    return tuple(tuple(r) for r in out)
