"""PyLayer: user-defined differentiable ops.

Reference: python/paddle/autograd/py_layer.py (PyLayer/PyLayerContext backed
by C++ pylayer GradNode, fluid/eager/pylayer/py_layer_node.h).
"""
from __future__ import annotations

from typing import Any, List

from . import engine


class PyLayerContext:
    def __init__(self):
        self._saved: List[Any] = []
        self.materialize_grads = True
        self._extra = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    # arbitrary attributes allowed (mirrors reference ctx usage)
    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class _PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=_PyLayerMeta):
    """Subclass with @staticmethod forward(ctx, *args) and
    backward(ctx, *grad_outputs); call via .apply()."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor
        from ..core import dispatch

        ctx = PyLayerContext()
        with engine.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = isinstance(outs, Tensor)
        outs_list = [outs] if single else list(outs)
        out_arrays = [o._value for o in outs_list]

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]

        prim_name = f"pylayer::{cls.__module__}.{cls.__qualname__}"
        if prim_name not in dispatch.PRIMITIVES:

            def _vjp(grads_out, saved_ctx, **static):
                layer_cls, saved_ctx, n_inputs = saved_ctx
                gts = [Tensor._from_value(g) for g in grads_out]
                with engine.no_grad():
                    gin = layer_cls.backward(saved_ctx, *gts)
                gin = [gin] if isinstance(gin, Tensor) or gin is None else list(gin)
                res = [
                    None if g is None else (g._value if isinstance(g, Tensor) else g)
                    for g in gin
                ]
                if len(res) != n_inputs:
                    raise RuntimeError(
                        f"{layer_cls.__name__}.backward returned {len(res)} "
                        f"grads for {n_inputs} tensor inputs"
                    )
                return tuple(res)

            dispatch.register_primitive(
                prim_name, forward=None, vjp=_vjp, jittable=False
            )

        node = engine.record_op(
            prim_name,
            {},
            (cls, ctx, len(tensor_inputs)),
            tensor_inputs,
            out_arrays,
            # the reference PyLayer records unconditionally: a custom
            # backward may have side effects (e.g. PS push_sparse) or feed
            # internal parameters even when no INPUT requires grad
            force=True,
        )
        requires = node is not None
        wrapped = []
        for i, o in enumerate(out_arrays):
            t = Tensor._from_value(o, stop_gradient=not requires)
            if node is not None:
                t._node = node
                t._out_slot = i
            wrapped.append(t)
        return wrapped[0] if single else tuple(wrapped)


LegacyPyLayer = PyLayer
