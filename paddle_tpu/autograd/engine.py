"""Eager autograd engine.

TPU-native re-design of the reference eager autograd
(reference: paddle/fluid/eager/grad_node_info.h:197 GradNodeBase / :53 Edge,
backward.cc:105 RunBackward with in-degree topo sort at :23,
grad_tensor_holder.cc GradTensorHolder accumulation,
tensor_wrapper.h saved-tensor wrappers).

Design: a tape of GradNodes is recorded as primitives execute. Nodes hold raw
jax arrays (concrete in eager mode, tracers under ``jit.to_static`` capture),
so ONE engine serves both execution modes — backward inside a traced step
becomes part of the compiled XLA program and fuses with forward.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..core import dispatch

# --------------------------------------------------------------------------
# grad-recording state (paddle.no_grad / enable_grad)
# --------------------------------------------------------------------------
_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


class no_grad:
    """paddle.no_grad parity (context manager + decorator)."""

    def __enter__(self):
        self._prev = grad_enabled()
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = grad_enabled()
        _state.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)
    return no_grad() if not mode else enable_grad()


# --------------------------------------------------------------------------
# Graph nodes
# --------------------------------------------------------------------------
class AccumulationNode:
    """Grad sink for a leaf tensor (GradNodeAccumulation analog)."""

    __slots__ = ("tensor_ref",)

    def __init__(self, tensor):
        import weakref

        self.tensor_ref = weakref.ref(tensor)

    def accumulate(self, grad):
        t = self.tensor_ref()
        if t is None:
            return
        for hook in t._grad_hooks:
            new = hook(_wrap_grad(grad, t))
            if new is not None:
                grad = new._value if hasattr(new, "_value") else new
        if t._grad_value is None:
            t._grad_value = grad
        else:
            t._grad_value = t._grad_value + grad


def _wrap_grad(grad, like):
    from ..core.tensor import Tensor

    return Tensor._from_value(grad)


class GradNode:
    """One recorded primitive application (GradNodeBase analog).

    in_edges[i] is (producer: GradNode|AccumulationNode, slot: int) for each
    differentiable input, or None when that input needs no grad.
    """

    __slots__ = (
        "prim_name",
        "static",
        "saved",
        "saved_tensors",
        "out_avals",
        "in_edges",
        "out_hooks",
        "capture_slots",
        "name_hint",
    )

    def __init__(self, prim_name, static, saved, out_avals, in_edges,
                 saved_tensors=None):
        self.prim_name = prim_name
        self.static = static
        self.saved = saved
        # input Tensor refs: keep the upstream graph reachable for
        # create_graph double backward — the TensorWrapper analog
        # (fluid/eager/tensor_wrapper.h keeps the autograd graph of saved
        # tensors for higher-order grad). Trade-off: prims with a slim
        # custom save (e.g. save=()) now also retain their input arrays
        # until release(); paddle pays the same via TensorWrapper.
        self.saved_tensors = saved_tensors
        self.out_avals = out_avals  # [(shape, dtype)] per forward output
        self.in_edges: List[Optional[Tuple[Any, int]]] = in_edges
        self.out_hooks: Dict[int, List[Callable]] = {}
        self.capture_slots: Dict[int, Any] = {}
        self.name_hint = prim_name

    def release(self):
        self.saved = None
        self.saved_tensors = None

    def __repr__(self):
        return f"<GradNode {self.name_hint}>"


def record_op(prim_name, static, saved, in_tensors, out_arrays,
              saved_tensors=None, force=False):
    """Create the GradNode for a primitive call; returns it (or None when
    nothing requires grad / grad is disabled). Mirrors the node-creation block
    eager_gen.py emits into every *_ad_func (eager_gen.py:1132).

    force=True records the node even when no INPUT requires grad — needed by
    opaque-backward blocks (recompute/PyLayer) whose internal parameters
    still need gradients (the reference PyLayer records unconditionally)."""
    if not grad_enabled():
        return None
    edges: List[Optional[Tuple[Any, int]]] = []
    any_grad = False
    for t in in_tensors:
        if t is None or t.stop_gradient:
            edges.append(None)
            continue
        any_grad = True
        if t._node is not None:
            edges.append((t._node, t._out_slot))
        else:
            edges.append((t._accum_node(), 0))
    if not any_grad and not force:
        return None
    out_avals = [(tuple(o.shape), o.dtype) for o in out_arrays]
    if _saved_tensor_hooks and saved is not None:
        # saved_tensors_hooks pack stage (reference:
        # autograd/saved_tensors_hooks.py — wrap each saved array; the
        # unpack fn is captured so backward works after the context exits)
        pack, unpack = _saved_tensor_hooks[-1]
        saved = _SavedPacked(tuple(pack(a) for a in saved), unpack)
    return GradNode(prim_name, static, saved, out_avals, edges,
                    saved_tensors=saved_tensors)


_saved_tensor_hooks: List[Tuple[Any, Any]] = []


class _SavedPacked:
    """Marker wrapping hook-packed saved tensors until backward unpacks."""

    __slots__ = ("payload", "unpack_fn")

    def __init__(self, payload, unpack_fn):
        self.payload = payload
        self.unpack_fn = unpack_fn

    def unpack(self):
        return tuple(self.unpack_fn(a) for a in self.payload)


# --------------------------------------------------------------------------
# Backward execution (RunBackward analog, backward.cc:105)
# --------------------------------------------------------------------------
def _collect_indegree(roots: Sequence[GradNode]):
    """BFS the consumer graph to count, per node, how many times it is
    referenced as a producer (backward.cc:23 getInDegreeMap)."""
    indeg: Dict[int, int] = {}
    nodes: Dict[int, Any] = {}
    seen = set()
    q = deque(roots)
    for r in roots:
        seen.add(id(r))
        nodes[id(r)] = r
        indeg.setdefault(id(r), 0)
    while q:
        n = q.popleft()
        if isinstance(n, AccumulationNode):
            continue
        for e in n.in_edges:
            if e is None:
                continue
            p, _slot = e
            indeg[id(p)] = indeg.get(id(p), 0) + 1
            if id(p) not in seen:
                seen.add(id(p))
                nodes[id(p)] = p
                q.append(p)
    return indeg, nodes


def run_backward(
    tensors,
    grad_tensors=None,
    retain_graph: bool = False,
    capture: Optional[Dict[Tuple[int, int], Any]] = None,
    accumulate_leaves: bool = True,
):
    """Execute reverse-mode over the recorded tape.

    tensors: output Tensors to seed.  grad_tensors: matching seeds (or None
    → ones).  capture: optional {(id(node), slot): key} map — grads for those
    (node, slot) pairs are returned keyed by ``key`` instead of / in addition
    to leaf accumulation (GeneralGrad analog for paddle.grad).
    """
    from ..core.tensor import Tensor

    capture = capture or {}
    captured: Dict[Any, Any] = {}

    roots: List[GradNode] = []
    buffers: Dict[int, List[Optional[Any]]] = {}

    with no_grad():
        for i, t in enumerate(tensors):
            if t.stop_gradient and t._node is None:
                raise RuntimeError(
                    f"backward(): tensor {i} has stop_gradient=True and no grad graph"
                )
            g = None
            if grad_tensors is not None and grad_tensors[i] is not None:
                gt = grad_tensors[i]
                g = gt._value if isinstance(gt, Tensor) else jnp.asarray(gt)
            else:
                if t._value.size != 1:
                    if grad_tensors is None:
                        g = jnp.ones(t.shape, t.dtype)
                else:
                    g = jnp.ones(t.shape, t.dtype)
            node = t._node
            if node is None:
                # leaf with requires-grad: grad of itself is the seed
                acc = t._accum_node()
                key = capture.get((id(acc), 0))
                if key is not None:
                    captured[key] = g
                if accumulate_leaves:
                    acc.accumulate(g)
                continue
            if id(node) not in buffers:
                buffers[id(node)] = [None] * len(node.out_avals)
                roots.append(node)
            buf = buffers[id(node)]
            slot = t._out_slot
            buf[slot] = g if buf[slot] is None else buf[slot] + g

        if not roots:
            return captured

        indeg, nodes = _collect_indegree(roots)
        ready = deque(n for n in roots if indeg[id(n)] == 0)
        # roots referenced by other roots wait for their contributions
        processed = set()

        while ready:
            node = ready.popleft()
            if id(node) in processed:
                continue
            processed.add(id(node))
            buf = buffers.pop(id(node), [None] * len(node.out_avals))
            # fill zeros for outputs never used downstream (GradTensorHolder
            # fills with zeros-like, grad_tensor_holder.cc)
            grads_out = tuple(
                b
                if b is not None
                else jnp.zeros(shape, dtype)
                for b, (shape, dtype) in zip(buf, node.out_avals)
            )
            # per-(node,slot) hooks and captures fire on the finalized grad
            for slot, hooks in node.out_hooks.items():
                g = grads_out[slot]
                for hook in hooks:
                    new = hook(Tensor._from_value(g))
                    if new is not None:
                        g = new._value if isinstance(new, Tensor) else new
                grads_out = grads_out[:slot] + (g,) + grads_out[slot + 1 :]
            for slot in range(len(node.out_avals)):
                key = capture.get((id(node), slot))
                if key is not None:
                    captured[key] = grads_out[slot]

            if node.saved is None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time; "
                    "set retain_graph=True to allow this."
                )
            saved = (node.saved.unpack()
                     if isinstance(node.saved, _SavedPacked) else node.saved)
            in_grads = dispatch.call_vjp(
                node.prim_name, grads_out, saved, node.static
            )
            if not retain_graph:
                node.release()

            for e, g in zip(node.in_edges, in_grads):
                if e is None or g is None:
                    continue
                p, slot = e
                if isinstance(p, AccumulationNode):
                    key = capture.get((id(p), 0))
                    if key is not None:
                        captured[key] = (
                            g if key not in captured else captured[key] + g
                        )
                    if accumulate_leaves:
                        p.accumulate(g)
                    continue
                b = buffers.setdefault(id(p), [None] * len(p.out_avals))
                b[slot] = g if b[slot] is None else b[slot] + g
                indeg[id(p)] -= 1
                if indeg[id(p)] == 0:
                    ready.append(p)

        # nodes whose indegree never hit zero are unreachable-from-seed
        # consumers; any buffered grads there are simply dropped (matches
        # reference partial-graph semantics).
    return captured


# --------------------------------------------------------------------------
# create_graph (double backward): replay the backward pass THROUGH the
# primitive-application layer so every gradient computation is itself
# recorded on the tape. Each forward primitive gets a derived "__vjp__"
# primitive whose forward runs its backward rule; nesting is handled by
# jax's nested vjp in the generic fallback. Reference analog: GradNode
# backward functions are themselves differentiable ops when TensorWrappers
# keep the autograd graph (fluid/eager/general_grad.h + eager_gen VJP
# emission for higher-order ops).
# --------------------------------------------------------------------------
import jax as _jax


def _ensure_vjp_prim(prim_name: str) -> str:
    """Derived primitive running ``jax.vjp`` over the forward with the
    ORIGINAL inputs. Custom save/vjp fast paths are deliberately bypassed:
    they may save forward outputs (severing input dependence), while
    rematerialising the forward keeps every second-order path intact and
    XLA CSE/fusion absorbs the recompute."""
    vname = f"__vjp__{prim_name}"
    if vname in dispatch.PRIMITIVES:
        return vname
    prim = dispatch.PRIMITIVES[prim_name]

    def vjp_forward(*arrays, n_out, inner):
        static = dict(inner)
        grads_out = arrays[:n_out]
        inputs = arrays[n_out:]
        f = lambda *a: prim.forward(*a, **static)
        outs, vjp_fn = _jax.vjp(f, *inputs)
        grads = vjp_fn(grads_out if isinstance(outs, tuple) else grads_out[0])
        grads = tuple(grads) if isinstance(grads, (tuple, list)) else (grads,)
        # non-differentiable inputs (ints, PRNG keys) yield None/float0
        # cotangents — replace with float32 zero placeholders; their edges
        # are None so the placeholders are never consumed
        from jax.dtypes import float0

        return tuple(
            jnp.zeros(a.shape, jnp.float32)
            if g is None or getattr(g, "dtype", None) == float0
            else g
            for g, a in zip(grads, inputs)
        )

    dispatch.register_primitive(
        vname, vjp_forward, multi_out=True, jittable=prim.jittable
    )
    return vname


def run_backward_create_graph(
    tensors,
    grad_tensors=None,
    capture: Optional[Dict[Tuple[int, int], Any]] = None,
    retain_graph: bool = True,
):
    """Backward pass where gradients are Tensors on the live tape, enabling
    paddle.grad(..., create_graph=True) and arbitrary-order derivatives."""
    from ..core.tensor import Tensor, apply as tensor_apply

    capture = capture or {}
    captured: Dict[Any, Any] = {}
    buffers: Dict[int, List[Optional[Any]]] = {}
    roots: List[GradNode] = []

    def seed_for(t, i):
        if grad_tensors is not None and grad_tensors[i] is not None:
            gt = grad_tensors[i]
            return gt if isinstance(gt, Tensor) else Tensor._from_value(jnp.asarray(gt))
        return Tensor._from_value(jnp.ones(t.shape, t.dtype))

    for i, t in enumerate(tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError(
                f"backward(): tensor {i} has stop_gradient=True and no grad graph"
            )
        g = seed_for(t, i)
        node = t._node
        if node is None:
            acc = t._accum_node()
            key = capture.get((id(acc), 0))
            if key is not None:
                captured[key] = g if key not in captured else captured[key] + g
            continue
        if id(node) not in buffers:
            buffers[id(node)] = [None] * len(node.out_avals)
            roots.append(node)
        buf = buffers[id(node)]
        slot = t._out_slot
        buf[slot] = g if buf[slot] is None else buf[slot] + g

    if not roots:
        return captured

    indeg, _nodes = _collect_indegree(roots)
    ready = deque(n for n in roots if indeg[id(n)] == 0)
    processed = set()

    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        buf = buffers.pop(id(node), [None] * len(node.out_avals))
        grads_out = [
            b if b is not None else Tensor._from_value(jnp.zeros(shape, dtype))
            for b, (shape, dtype) in zip(buf, node.out_avals)
        ]
        for slot, hooks in node.out_hooks.items():
            g = grads_out[slot]
            for hook in hooks:
                new = hook(g)
                if new is not None:
                    g = new if isinstance(new, Tensor) else Tensor._from_value(new)
            grads_out[slot] = g
        for slot in range(len(node.out_avals)):
            key = capture.get((id(node), slot))
            if key is not None:
                captured[key] = grads_out[slot]

        if node.saved is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "set retain_graph=True to allow this."
            )
        prim = dispatch.PRIMITIVES[node.prim_name]
        if node.saved_tensors is None or prim.forward is None:
            # non-replayable node (PyLayer / recompute: opaque Python
            # backward, no jax forward to differentiate) — run its
            # first-order vjp; the produced grads enter the new tape as
            # constants, so second order THROUGH this node is cut, matching
            # the reference's behavior for non-double-grad custom ops
            raw = dispatch.call_vjp(
                node.prim_name,
                tuple(g._value for g in grads_out),
                node.saved.unpack() if isinstance(node.saved, _SavedPacked)
                else node.saved,
                node.static,
            )
            in_grads = tuple(
                None if g is None else Tensor._from_value(g) for g in raw
            )
        else:
            vname = _ensure_vjp_prim(node.prim_name)
            in_grads = tensor_apply(
                vname, *grads_out, *node.saved_tensors,
                n_out=len(grads_out),
                inner=dispatch._hashable(node.static),
            )
            if not isinstance(in_grads, tuple):
                in_grads = (in_grads,)
        if not retain_graph:
            node.release()

        for e, g in zip(node.in_edges, in_grads):
            if e is None or g is None:
                continue
            p, slot = e
            if isinstance(p, AccumulationNode):
                key = capture.get((id(p), 0))
                if key is not None:
                    captured[key] = g if key not in captured else captured[key] + g
                continue
            b = buffers.setdefault(id(p), [None] * len(p.out_avals))
            b[slot] = g if b[slot] is None else b[slot] + g
            indeg[id(p)] -= 1
            if indeg[id(p)] == 0:
                ready.append(p)

    return captured
