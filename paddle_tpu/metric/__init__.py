"""paddle.metric parity.

Reference: python/paddle/metric/metrics.py (Metric base, Accuracy,
Precision, Recall, Auc) + paddle.metric.accuracy functional.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        from ..ops.manipulation import argsort

        pv = np.asarray(pred._value) if isinstance(pred, Tensor) else np.asarray(pred)
        lv = np.asarray(label._value) if isinstance(label, Tensor) else np.asarray(label)
        if lv.ndim == pv.ndim and lv.shape[-1] == 1:
            lv = lv[..., 0]
        top = np.argsort(-pv, axis=-1)[..., : self.maxk]
        correct = top == lv[..., None]
        return Tensor._from_value(np.asarray(correct, np.float32))

    def update(self, correct, *args):
        cv = np.asarray(correct._value) if isinstance(correct, Tensor) else np.asarray(correct)
        num = cv.shape[0] if cv.ndim > 0 else 1
        accs = []
        for i, k in enumerate(self.topk):
            c = cv[..., :k].sum()
            self.total[i] += c
            self.count[i] += num
            accs.append(c / max(num, 1))
        return np.asarray(accs[0] if len(accs) == 1 else accs)

    def accumulate(self):
        res = self.total / np.maximum(self.count, 1)
        return float(res[0]) if len(self.topk) == 1 else res.tolist()

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        pv = np.asarray(preds._value) if isinstance(preds, Tensor) else np.asarray(preds)
        lv = np.asarray(labels._value) if isinstance(labels, Tensor) else np.asarray(labels)
        pred_cls = (pv > 0.5).astype(np.int64).reshape(-1)
        lv = lv.reshape(-1)
        self.tp += int(((pred_cls == 1) & (lv == 1)).sum())
        self.fp += int(((pred_cls == 1) & (lv == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        pv = np.asarray(preds._value) if isinstance(preds, Tensor) else np.asarray(preds)
        lv = np.asarray(labels._value) if isinstance(labels, Tensor) else np.asarray(labels)
        pred_cls = (pv > 0.5).astype(np.int64).reshape(-1)
        lv = lv.reshape(-1)
        self.tp += int(((pred_cls == 1) & (lv == 1)).sum())
        self.fn += int(((pred_cls == 0) & (lv == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        pv = np.asarray(preds._value) if isinstance(preds, Tensor) else np.asarray(preds)
        lv = np.asarray(labels._value) if isinstance(labels, Tensor) else np.asarray(labels)
        pos_prob = pv[:, 1] if pv.ndim == 2 else pv.reshape(-1)
        lv = lv.reshape(-1)
        bins = np.round(pos_prob * self.num_thresholds).astype(np.int64)
        for b, l in zip(bins, lv):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2.0
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """paddle.metric.accuracy functional."""
    import jax.numpy as jnp

    from ..ops._helpers import ensure_tensor

    input, label = ensure_tensor(input), ensure_tensor(label)
    pv, lv = input._value, label._value
    if lv.ndim == 2 and lv.shape[1] == 1:
        lv = lv[:, 0]
    import jax

    _, topi = jax.lax.top_k(pv, k)
    correct_ = jnp.any(topi == lv[:, None], axis=1)
    return Tensor._from_value(jnp.mean(correct_.astype(jnp.float32)))
