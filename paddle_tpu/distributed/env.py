"""Distributed environment bring-up.

Reference: python/paddle/distributed/parallel.py:977 init_parallel_env
(TCPStore rendezvous at :1134, ProcessGroup creation :1137), env vars set by
the launcher (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER).

TPU re-design: one process per HOST (not per chip); jax.distributed.initialize
is the TCPStore+ncclCommInitRank analog (coordinator address ≈ master store).
Within a host, all local chips belong to this process, so "rank" here is the
host process index and device parallelism is expressed through meshes, not
extra processes (SURVEY §2.4 / §7 step 6).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def _env_int(*names, default=0):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return default


def get_rank(group=None) -> int:
    """paddle.distributed.get_rank parity. Process (host) index."""
    if group is not None:
        return group.get_group_rank(get_rank())
    if _initialized:
        return jax.process_index()
    return _env_int("PADDLE_TRAINER_ID", "RANK", default=0)


def get_world_size(group=None) -> int:
    """paddle.distributed.get_world_size parity (host processes)."""
    if group is not None:
        return group.nranks
    if _initialized:
        return jax.process_count()
    return _env_int("PADDLE_TRAINERS_NUM", "WORLD_SIZE", default=1)


def device_count() -> int:
    """Total accelerator devices across all hosts."""
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_initialized() -> bool:
    return _initialized


def init_parallel_env(strategy=None):
    """paddle.distributed.init_parallel_env parity (parallel.py:977).

    Single host: no-op beyond validating devices. Multi-host: reads the
    master endpoint from env (PADDLE_MASTER / MASTER_ADDR:MASTER_PORT) and
    calls jax.distributed.initialize — the TCPStore + comm-context bring-up
    collapse into the JAX coordination service over DCN.
    """
    global _initialized
    if _initialized:
        return _default_group()
    nprocs = _env_int("PADDLE_TRAINERS_NUM", "WORLD_SIZE", default=1)
    if nprocs > 1:
        master = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        if master and port and ":" not in master:
            master = f"{master}:{port}"
        rank = _env_int("PADDLE_TRAINER_ID", "RANK", default=0)
        jax.distributed.initialize(
            coordinator_address=master, num_processes=nprocs, process_id=rank
        )
    _initialized = True
    return _default_group()


def _default_group():
    from .communication.group import _get_or_create_default_group

    return _get_or_create_default_group()


def barrier(group=None):
    """paddle.distributed.barrier parity: a psum over all devices forces a
    cross-host sync point."""
    import jax.numpy as jnp

    devs = jax.devices()
    if len(devs) <= 1:
        return
    from jax.experimental import multihost_utils

    try:
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def get_backend() -> str:
    return "xla"  # ICI/DCN collectives via XLA (NCCL analog)
