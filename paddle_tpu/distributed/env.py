"""Distributed environment bring-up.

Reference: python/paddle/distributed/parallel.py:977 init_parallel_env
(TCPStore rendezvous at :1134, ProcessGroup creation :1137), env vars set by
the launcher (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER).

TPU re-design: one process per HOST (not per chip); jax.distributed.initialize
is the TCPStore+ncclCommInitRank analog (coordinator address ≈ master store).
Within a host, all local chips belong to this process, so "rank" here is the
host process index and device parallelism is expressed through meshes, not
extra processes (SURVEY §2.4 / §7 step 6).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def _env_int(*names, default=0):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return default


def get_rank(group=None) -> int:
    """paddle.distributed.get_rank parity. Process (host) index."""
    if group is not None:
        return group.get_group_rank(get_rank())
    if _initialized:
        return jax.process_index()
    return _env_int("PADDLE_TRAINER_ID", "RANK", default=0)


def get_world_size(group=None) -> int:
    """paddle.distributed.get_world_size parity (host processes)."""
    if group is not None:
        return group.nranks
    if _initialized:
        return jax.process_count()
    return _env_int("PADDLE_TRAINERS_NUM", "WORLD_SIZE", default=1)


def device_count() -> int:
    """Total accelerator devices across all hosts."""
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_initialized() -> bool:
    return _initialized


_store = None
_barrier_epoch = 0
_key_prefix = "g0/"


def get_store():
    """The process's rendezvous store (reference: the global TCPStore made
    at parallel.py:1134). None before init_parallel_env."""
    return _store


def init_parallel_env(strategy=None):
    """paddle.distributed.init_parallel_env parity (parallel.py:977).

    Single host: no-op beyond validating devices. Multi-host: (1) build the
    TCPStore rendezvous (rank 0 hosts the server — parallel.py:1134), (2)
    register this rank and wait for the full world, (3) on TPU backends,
    call jax.distributed.initialize (coordinator on master port+1) — the
    comm-context bring-up collapses into the JAX coordination service over
    DCN. On CPU rigs the store IS the rendezvous and jax stays
    single-process (the reference's gloo-only path).
    """
    global _initialized, _store
    if _initialized:
        return _default_group()
    nprocs = _env_int("PADDLE_TRAINERS_NUM", "WORLD_SIZE", default=1)
    if nprocs > 1:
        master = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        if master and port and ":" not in master:
            master = f"{master}:{port}"
        rank = _env_int("PADDLE_TRAINER_ID", "RANK", default=0)

        from .store import create_store

        _store = create_store(master, rank, nprocs)
        # rendezvous keys are namespaced by the restart generation the
        # launcher hands down (PADDLE_RESTART_GEN): a restarted worker must
        # not satisfy its rendezvous/barriers from a previous incarnation's
        # stale keys
        gen = os.environ.get("PADDLE_RESTART_GEN", "0")
        global _key_prefix
        _key_prefix = f"g{gen}/"
        _store.set(f"{_key_prefix}worker/{rank}", str(os.getpid()))
        _store.add(f"{_key_prefix}worker_count", 1)
        _store.wait([f"{_key_prefix}worker/{r}" for r in range(nprocs)])

        use_jax = os.environ.get("PADDLE_USE_JAX_COORDINATOR", "auto")
        # Decide WITHOUT querying devices: jax.distributed.initialize must
        # run before any backend-initializing call (jax.devices etc.), so
        # probe env vars only. TPU pods set TPU_WORKER_ID / megascale vars.
        on_accel = use_jax == "1" or (
            use_jax == "auto" and (
                os.environ.get("TPU_WORKER_ID") is not None
                or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") is not None
            )
        )
        if on_accel and master:
            # CPU rigs reduce over gloo (the reference's gloo-only path);
            # harmless on TPU where collectives ride ICI/DCN
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
            host, p = master.rsplit(":", 1)
            jax.distributed.initialize(
                coordinator_address=f"{host}:{int(p) + 1}",
                num_processes=nprocs, process_id=rank,
            )
    _initialized = True
    return _default_group()


def _default_group():
    from .communication.group import _get_or_create_default_group

    return _get_or_create_default_group()


def barrier(group=None):
    """paddle.distributed.barrier parity. Multi-process: counter rendezvous
    through the store (reference: Barrier at process_group.h:167). On a
    multi-host device runtime, also syncs global devices."""
    global _barrier_epoch
    nprocs = _env_int("PADDLE_TRAINERS_NUM", "WORLD_SIZE", default=1)
    if _store is not None and nprocs > 1:
        _barrier_epoch += 1
        key = f"{_key_prefix}barrier/{_barrier_epoch}"
        from ..core.flags import get_flag
        from .communication.watchdog import get_comm_task_manager

        deadline = float(get_flag("stop_check_timeout"))
        import time as _time

        with get_comm_task_manager().task(f"barrier#{_barrier_epoch}",
                                          timeout_s=deadline):
            _store.add(key, 1)
            t0 = _time.time()
            while int(_store.get(key)) < nprocs:
                if _time.time() - t0 > deadline:
                    raise TimeoutError("barrier timed out")
                _time.sleep(0.01)
    devs = jax.devices()
    if len(devs) <= 1:
        return
    from jax.experimental import multihost_utils

    try:
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def get_backend() -> str:
    return "xla"  # ICI/DCN collectives via XLA (NCCL analog)
