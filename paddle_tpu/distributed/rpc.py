"""RPC framework — ``paddle.distributed.rpc`` parity.

Reference: python/paddle/distributed/rpc/rpc.py (init_rpc :73, rpc_sync
:143, rpc_async :183, shutdown :276, get_worker_info :307) over brpc.
Here: a threaded TCP server per worker executing pickled callables
(length-prefixed frames), with the framework TCPStore as the rendezvous
that exchanges (name, ip, port) triples — the same trust model as the
reference (serialized Python between cluster peers).

The module-level API drives one process-global agent; the ``RpcAgent``
class underneath is instantiable directly, which is how the tests run a
multi-worker topology inside one process."""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from collections import namedtuple
from concurrent.futures import Future
from typing import Dict, Optional

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "WorkerInfo",
           "RpcAgent"]

_DEFAULT_TIMEOUT = 30.0


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _send_msg(sock, obj):
    raw = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(raw)) + raw)


def _recv_msg(sock):
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


def _routable_ip() -> str:
    """This host's address as peers should dial it: POD_IP (the launcher
    env contract) when set, else the interface a default route uses,
    falling back to loopback for single-host runs."""
    import os

    ip = os.environ.get("POD_IP")
    if ip:
        return ip
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class RpcAgent:
    """One RPC endpoint: serves incoming calls, issues outgoing ones."""

    def __init__(self, name: str, rank: int, host: str = "0.0.0.0", port: int = 0,
                 advertise_ip: Optional[str] = None):
        self.name = name
        self.rank = rank
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        bound_ip, self.port = self._sock.getsockname()
        # advertise a peer-dialable address, not the wildcard bind
        self.ip = advertise_ip or (
            bound_ip if bound_ip not in ("0.0.0.0", "::") else _routable_ip()
        )
        self._stop = threading.Event()
        self._workers: Dict[str, WorkerInfo] = {}
        self._accept_thread = threading.Thread(target=self._serve, daemon=True)
        self._accept_thread.start()

    @property
    def info(self) -> WorkerInfo:
        return WorkerInfo(self.name, self.rank, self.ip, self.port)

    def register_workers(self, infos):
        self._workers = {i.name: WorkerInfo(*i) for i in infos}

    # -- serving --------------------------------------------------------
    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    msg = _recv_msg(conn)
                except (ConnectionError, OSError, EOFError):
                    return
                kind = msg[0]
                if kind == "call":
                    _, fn, args, kwargs = msg
                    try:
                        result = fn(*(args or ()), **(kwargs or {}))
                        _send_msg(conn, ("ok", result))
                    except Exception as e:  # noqa: BLE001 — shipped to caller
                        _send_msg(conn, ("err", e))
        finally:
            conn.close()

    # -- calling --------------------------------------------------------
    def _call(self, to: str, fn, args, kwargs, timeout):
        w = self._workers.get(to)
        if w is None:
            raise ValueError(f"unknown rpc worker: {to!r}")
        with socket.create_connection((w.ip, w.port),
                                      timeout=None if timeout <= 0 else timeout) as s:
            _send_msg(s, ("call", fn, args, kwargs))
            status, payload = _recv_msg(s)
        if status == "err":
            raise payload
        return payload

    def rpc_sync(self, to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
        return self._call(to, fn, args, kwargs, timeout)

    def rpc_async(self, to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self._call(to, fn, args, kwargs, timeout))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        fut.wait = fut.result  # paddle returns an object with .wait()
        return fut

    def stop(self):
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            poke = socket.create_connection((self.ip, self.port), timeout=1)
            poke.close()
        except OSError:
            pass
        self._sock.close()


# ---------------------------------------------------------------------------
# module-level API over one process-global agent
# ---------------------------------------------------------------------------
_agent: Optional[RpcAgent] = None
_store = None


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this process's RPC agent and exchange worker infos through the
    TCPStore rendezvous (reference init_rpc :73; env fallbacks
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER)."""
    global _agent, _store
    import os

    from .store import TCPStore

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)
    agent = RpcAgent(name, rank)
    if world_size > 1:
        master_endpoint = master_endpoint or os.environ.get("PADDLE_MASTER")
        if master_endpoint is None:
            raise ValueError("master_endpoint required for world_size > 1")
        host, port = master_endpoint.rsplit(":", 1)
        _store = TCPStore(host, int(port), is_master=(rank == 0),
                          world_size=world_size)
        _store.set(f"rpc/{rank}", pickle.dumps(tuple(agent.info)))
        infos = []
        for r in range(world_size):
            infos.append(WorkerInfo(*pickle.loads(_store.get(f"rpc/{r}"))))
    else:
        infos = [agent.info]
    agent.register_workers(infos)
    _agent = agent
    return agent


def _require_agent() -> RpcAgent:
    if _agent is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _agent


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    return _require_agent().rpc_sync(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    return _require_agent().rpc_async(to, fn, args, kwargs, timeout)


def get_worker_info(name):
    return _require_agent()._workers[name]


def get_all_worker_infos():
    return list(_require_agent()._workers.values())


def get_current_worker_info():
    return _require_agent().info


def shutdown():
    """Stop the local agent (reference shutdown :276 barriers then stops;
    single-controller tests stop directly)."""
    global _agent, _store
    if _agent is not None:
        _agent.stop()
        _agent = None
    if _store is not None:
        try:
            _store.close()
        except Exception:  # noqa: BLE001
            pass
        _store = None
