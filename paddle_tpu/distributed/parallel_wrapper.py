"""DataParallel wrapper.

Reference: python/paddle/distributed/parallel.py:218 DataParallel + C++
EagerReducer (fluid/distributed/collective/reducer.cc:543-951 — bucketed
grad allreduce overlapped with backward).

TPU re-design: DP is batch sharding over the 'dp' mesh axis. Params are
replicated; inputs sharded on dim 0; under a compiled train step XLA emits
ONE fused gradient all-reduce schedule overlapped with backward compute —
the reducer's bucketing/overlap machinery is the compiler's job on TPU.
Eager single-chip falls back to plain execution.
"""
from __future__ import annotations

from typing import Optional

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .auto_parallel.api import shard_tensor
from .auto_parallel.placement import ProcessMesh, Replicate, Shard


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1, find_unused_parameters=False,
                 group=None, mesh: Optional[ProcessMesh] = None):
        super().__init__()
        self._layers = layers
        self._mesh = mesh
        if mesh is None:
            from .fleet.topology import get_hybrid_communicate_group

            hcg = get_hybrid_communicate_group()
            if hcg is not None and hcg.get_data_parallel_world_size() > 1:
                self._mesh = hcg.mesh
        if self._mesh is not None:
            repl = [Replicate() for _ in range(self._mesh.ndim)]
            for p in layers.parameters():
                if p._dist_attr is None:
                    shard_tensor(p, self._mesh, repl)

    def _shard_input(self, x):
        if self._mesh is None or not isinstance(x, Tensor):
            return x
        try:
            dp_axis = self._mesh.dim_names.index("dp")
        except ValueError:
            dp_axis = 0
        placements = [Replicate() for _ in range(self._mesh.ndim)]
        if x.ndim > 0 and x.shape[0] % self._mesh.shape[dp_axis] == 0:
            placements[dp_axis] = Shard(0)
        from .auto_parallel.api import reshard

        return reshard(x, self._mesh, placements)

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    # passthrough surface
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    @property
    def _layers_attr(self):
        return self._layers

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass
