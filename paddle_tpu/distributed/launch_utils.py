"""Launcher: paddle.distributed.launch / spawn.

Reference: python/paddle/distributed/launch/ — main.py CLI,
controllers/collective.py (builds a Pod of per-device Containers, wires
PADDLE_TRAINER_ID/endpoints env, master KV for multi-node rendezvous via
controllers/master.py, watches and restarts procs via
controllers/watcher.py).

TPU re-design: one worker process per HOST — all local chips belong to
that process and parallelism is mesh-addressed, so a "Pod" holds exactly
one Container (per-chip process fan-out is a CUDA-ism). Multi-node
rendezvous rides the native TCPStore (csrc/ptpu_tcp_store.cc); the node-0
launcher hosts the store server, every node's launcher registers, and the
watch loop restarts failed workers up to max_restarts (elastic relaunch
lives in distributed.elastic).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, List, Optional

#: exit code a worker uses to say "I am healthy but a PEER died — restart
#: me at the next generation". The controller seeing this code does NOT
#: bump the generation itself: the dead worker's own controller does
#: (its worker exited with a real failure code), so one incident makes
#: exactly one bump no matter how many survivors bail out.
ELASTIC_PEER_EXIT = 23

#: how long a controller whose worker exited with ELASTIC_PEER_EXIT waits
#: for the failed peer's controller to bump the shared generation before
#: concluding that controller died too and bumping on its own behalf.
PEER_BUMP_WAIT_S = 15.0


def spawn(func: Callable, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    """paddle.distributed.spawn parity. On TPU, nprocs>1 per host is an
    anti-pattern (chips are mesh-addressed, not process-addressed), so
    nprocs defaults to 1 and the function runs inline; multi-host spawn
    must go through the launch CLI on each host."""
    if nprocs not in (1, None):
        raise ValueError(
            "spawn(nprocs>1) is not supported on TPU: one process drives all "
            "local chips via the device mesh (use paddle.distributed.launch "
            "with --nnodes for multi-host)"
        )
    from . import env

    env.init_parallel_env()
    func(*args)


class Container:
    """One worker OS process (reference: launch/job/container.py)."""

    def __init__(self, cmd: List[str], env_vars: dict,
                 log_path: Optional[str]):
        self.cmd = cmd
        self.env_vars = env_vars
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0

    def start(self):
        out = open(self.log_path, "ab") if self.log_path else None
        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env_vars}, stdout=out,
            stderr=subprocess.STDOUT if out else None,
        )

    def poll(self):
        return None if self.proc is None else self.proc.poll()

    def wait(self, timeout=None):
        return self.proc.wait(timeout)

    def terminate(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class Pod:
    """This node's set of containers — exactly one on TPU
    (reference: launch/job/pod.py)."""

    def __init__(self, container: Container):
        self.containers = [container]

    def deploy(self):
        for c in self.containers:
            c.start()

    def join(self):
        return max(c.wait() for c in self.containers)

    def stop(self):
        for c in self.containers:
            c.terminate()


class CollectiveController:
    """Reference: launch/controllers/collective.py. Builds the pod env,
    runs the master rendezvous, deploys, and watches."""

    def __init__(self, training_script: str, args: List[str],
                 nnodes: int = 1, node_rank: int = 0,
                 master: Optional[str] = None, log_dir: str = "log",
                 max_restarts: int = 0, job_id: str = "default",
                 flight_dir: Optional[str] = None,
                 fleet_dir: Optional[str] = None,
                 metrics_dump: Optional[str] = None):
        self.training_script = training_script
        self.args = list(args)
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.master = master
        self.log_dir = log_dir
        self.max_restarts = max_restarts
        self.job_id = job_id
        self.flight_dir = flight_dir
        self.fleet_dir = fleet_dir
        self.metrics_dump = metrics_dump
        self._store = None
        self._aggregator = None

    # -- rendezvous (reference: controllers/master.py) -------------------
    def _rendezvous(self):
        if self.nnodes <= 1 or self.master is None:
            return
        from .store import create_store

        self._store = create_store(
            self.master, self.node_rank, self.nnodes
        )
        self._store.set(
            f"launch/{self.job_id}/node/{self.node_rank}",
            f"{os.getpid()}"
        )
        self._store.wait(
            [f"launch/{self.job_id}/node/{r}" for r in range(self.nnodes)]
        )

    def _build_pod(self) -> Pod:
        env_vars = {
            "PADDLE_TRAINERS_NUM": str(self.nnodes),
            "PADDLE_TRAINER_ID": str(self.node_rank),
            "PADDLE_JOB_ID": self.job_id,
        }
        if self.master:
            # the launcher's own store owns `port`; trainers rendezvous on
            # port+2 (port+1 is the jax coordinator — see env.py), mirroring
            # the reference's separate launcher-KV vs trainer-TCPStore
            host, port = self.master.rsplit(":", 1)
            env_vars["PADDLE_MASTER"] = f"{host}:{int(port) + 2}"
            # elastic heartbeats ride the LAUNCHER's store (hosted by the
            # node-0 controller, which outlives any worker): a rank-0
            # worker death must not take the liveness record down with it
            env_vars["PADDLE_ELASTIC_MASTER"] = self.master
            env_vars["PADDLE_ELASTIC_JOB_ID"] = self.job_id
        if self.flight_dir:
            # arm the PR-5 flight recorder in every worker: the env var
            # turns the observability gate on at import, so each worker
            # carries the event ring from step 0 and can dump on a peer
            # death without any code in the training script
            env_vars["PADDLE_TPU_FLIGHT_DIR"] = self.flight_dir
        # per-rank metrics dump: all workers inherit ONE
        # PADDLE_TPU_METRICS_DUMP path and their atexit dumps would
        # clobber each other — rewrite it to metrics.rank<N>.json
        # (mirrors the --flight_dir plumbing above; flight dumps embed
        # the pid in the filename so they never collided)
        metrics_dump = self.metrics_dump \
            or os.environ.get("PADDLE_TPU_METRICS_DUMP")
        if metrics_dump:
            from ..observability.fleet import rank_dump_path

            env_vars["PADDLE_TPU_METRICS_DUMP"] = rank_dump_path(
                metrics_dump, self.node_rank)
        if self.fleet_dir:
            # fleet telemetry: every worker ships registry/event
            # snapshots over the launcher-hosted elastic store; the
            # node-0 controller aggregates them into fleet_dir
            env_vars["PADDLE_TPU_FLEET"] = "1"
        os.makedirs(self.log_dir, exist_ok=True)
        cmd = [sys.executable, self.training_script] + self.args
        log = os.path.join(self.log_dir, f"workerlog.{self.node_rank}")
        return Pod(Container(cmd, env_vars, log))

    # -- watch loop (reference: controllers/watcher.py) ------------------
    def _gen_key(self):
        return f"launch/{self.job_id}/generation"

    def _peer_generation(self) -> int:
        if self._store is None:
            return 0
        try:
            return int(self._store.get(self._gen_key(), timeout_s=0))
        except Exception:
            return 0

    def run(self) -> int:
        """Watch loop. A collective job restarts as a WHOLE: when any
        node's worker fails, its controller bumps the shared generation
        counter; every controller notices, kills its (healthy) worker, and
        restarts at the new generation. Workers namespace rendezvous keys
        by generation (PADDLE_RESTART_GEN) so a restarted world can never
        satisfy barriers from the previous incarnation."""
        self._rendezvous()
        if self.fleet_dir and self.node_rank == 0 \
                and self._store is not None:
            # the launcher-anchored telemetry plane: this controller
            # hosts the store every worker ships snapshots through, so
            # the fleet view survives any worker's death
            from ..observability.fleet import FleetAggregator

            self._aggregator = FleetAggregator(
                self._store, self.nnodes, job_id=self.job_id,
                out_dir=self.fleet_dir)
            self._aggregator.start()
        pod = self._build_pod()
        container = pod.containers[0]
        generation = self._peer_generation()
        container.env_vars["PADDLE_RESTART_GEN"] = str(generation)
        container.start()
        while True:
            rc = container.poll()
            if rc is None:
                # healthy so far — did a peer trigger a restart?
                peer_gen = self._peer_generation()
                if peer_gen > generation:
                    container.terminate()
                    generation = peer_gen
                    container.restarts += 1
                    if container.restarts > self.max_restarts:
                        self._finalize(1)
                        return 1
                    container.env_vars["PADDLE_RESTART_GEN"] = str(generation)
                    container.start()
                time.sleep(0.5)
                continue
            if rc == 0:
                self._finalize(0)
                return 0
            container.restarts += 1
            if container.restarts > self.max_restarts:
                self._finalize(rc)
                return rc
            time.sleep(1)
            if rc == ELASTIC_PEER_EXIT and self._store is not None:
                # our worker is a SURVIVOR that bailed out of a dead
                # world: the failed peer's controller owns the generation
                # bump. Wait for it (one incident = one bump); only if
                # that controller vanished too do we bump ourselves.
                deadline = time.time() + PEER_BUMP_WAIT_S
                while time.time() < deadline:
                    peer_gen = self._peer_generation()
                    if peer_gen > generation:
                        break
                    time.sleep(0.2)
                else:
                    self._store.add(self._gen_key(), 1)
                generation = self._peer_generation()
            elif self._store is not None:
                # tell every other node to restart at the next generation
                generation = self._store.add(self._gen_key(), 1)
            else:
                generation += 1
            container.env_vars["PADDLE_RESTART_GEN"] = str(generation)
            container.start()

    def _finalize(self, rc: int):
        if self._store is None:
            return
        try:
            self._store.set(
                f"launch/{self.job_id}/done/{self.node_rank}", str(rc)
            )
            if self.node_rank == 0:
                # the master hosts the store server: keep it alive until
                # every node reported done (or a grace timeout), else peers
                # lose their rendezvous mid-shutdown
                self._store.wait(
                    [f"launch/{self.job_id}/done/{r}"
                     for r in range(self.nnodes)],
                    timeout_s=60,
                )
        except Exception:
            pass  # best-effort: a vanished master must not fail the job
        finally:
            if self._aggregator is not None:
                # after the done-key handshake: every node's controller
                # saw its worker exit, so every worker's final snapshot
                # is already in the store when this last poll runs
                try:
                    self._aggregator.stop()
                except Exception:
                    pass
                self._aggregator = None
            self._store.close()


def launch(training_script: str, args: List[str], nnodes: int = 1,
           node_rank: int = 0, master: Optional[str] = None,
           log_dir: str = "log", max_restarts: int = 0,
           job_id: str = "default", flight_dir: Optional[str] = None,
           fleet_dir: Optional[str] = None,
           metrics_dump: Optional[str] = None):
    """Programmatic launcher (CLI in paddle_tpu/distributed/launch/__main__.py).

    ``flight_dir`` arms the flight recorder in every spawned worker
    (sets ``PADDLE_TPU_FLIGHT_DIR``): on a peer death, watchdog timeout
    or crash, each worker writes a post-mortem JSON there.

    ``fleet_dir`` turns on fleet telemetry: workers ship metric/event
    snapshots through the launcher-hosted store and the node-0
    controller aggregates them into ``fleet_dir/fleet_metrics.json``
    (counters summed, gauges rank-labeled, step-skew/straggler
    detection) plus a merged clock-aligned ``fleet_trace.json``.

    ``metrics_dump`` (or an inherited ``PADDLE_TPU_METRICS_DUMP``) is
    rewritten per rank as ``<base>.rank<N>.json`` so workers never
    clobber one dump path."""
    return CollectiveController(
        training_script, args, nnodes, node_rank, master, log_dir,
        max_restarts, job_id, flight_dir, fleet_dir, metrics_dump,
    ).run()
