"""Launcher: paddle.distributed.launch / spawn.

Reference: python/paddle/distributed/launch/ (main.py CLI,
controllers/collective.py — one process per GPU, env wiring, watch loop).

TPU re-design: one worker process per HOST (all local chips belong to the
process); the launcher wires PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/
PADDLE_MASTER and restarts failed workers. Single-host multi-chip needs no
spawning at all — the mesh covers local devices — so `spawn(nprocs=1)` and
`launch` on one node simply exec the entry.
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys
import time
from typing import Callable, List, Optional


def spawn(func: Callable, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    """paddle.distributed.spawn parity. On TPU, nprocs>1 per host is an
    anti-pattern (chips are mesh-addressed, not process-addressed), so
    nprocs defaults to 1 and the function runs inline; multi-host spawn
    must go through the launch CLI on each host."""
    if nprocs not in (1, None):
        raise ValueError(
            "spawn(nprocs>1) is not supported on TPU: one process drives all "
            "local chips via the device mesh (use paddle.distributed.launch "
            "with --nnodes for multi-host)"
        )
    from . import env

    env.init_parallel_env()
    func(*args)


class _Worker:
    def __init__(self, cmd: List[str], env_vars: dict, log_path: Optional[str]):
        self.cmd = cmd
        self.env_vars = env_vars
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None

    def start(self):
        out = open(self.log_path, "ab") if self.log_path else None
        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env_vars}, stdout=out,
            stderr=subprocess.STDOUT if out else None,
        )


def launch(training_script: str, args: List[str], nnodes: int = 1,
           node_rank: int = 0, master: Optional[str] = None,
           log_dir: str = "log", max_restarts: int = 0):
    """Programmatic launcher (CLI in paddle_tpu/distributed/launch/__main__.py).

    Single node: exec inline. Multi node: set the coordination env and exec —
    actual remote process placement belongs to the cluster scheduler, as in
    the reference's non-elastic path."""
    env_vars = {
        "PADDLE_TRAINERS_NUM": str(nnodes),
        "PADDLE_TRAINER_ID": str(node_rank),
    }
    if master:
        env_vars["PADDLE_MASTER"] = master
    os.makedirs(log_dir, exist_ok=True)
    cmd = [sys.executable, training_script] + list(args)
    restarts = 0
    while True:
        w = _Worker(cmd, env_vars, os.path.join(log_dir, f"workerlog.{node_rank}"))
        w.start()
        rc = w.proc.wait()
        if rc == 0:
            return 0
        restarts += 1
        if restarts > max_restarts:
            return rc
        time.sleep(1)
