"""launch package."""
