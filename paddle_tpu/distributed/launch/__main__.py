"""CLI: python -m paddle_tpu.distributed.launch train.py [args...]

Reference: python/paddle/distributed/launch/__main__.py + main.py.
"""
import argparse
import sys

from ..launch_utils import launch


def main():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", type=str, default=None)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    a = p.parse_args()
    sys.exit(
        launch(a.training_script, a.training_script_args, a.nnodes, a.node_rank,
               a.master, a.log_dir, a.max_restarts)
    )


if __name__ == "__main__":
    main()
