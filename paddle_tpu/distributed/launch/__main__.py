"""CLI: python -m paddle_tpu.distributed.launch train.py [args...]

Reference: python/paddle/distributed/launch/__main__.py + main.py
(args parsed in launch/context/args_envs.py). TPU notes: --devices and
--nproc_per_node are accepted for parity but one worker process drives
all local chips (mesh-addressed), so per-chip fan-out args are no-ops.
"""
import argparse
import os
import sys

from ..launch_utils import launch


def main():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count, or elastic range 'min:max'")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", type=str, default=None,
                   help="host:port of the rendezvous store")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--flight_dir", type=str, default=None,
                   help="arm the flight recorder in every worker: "
                        "post-mortem JSON dumps (peer_death / rejoin / "
                        "crash) land in this directory")
    p.add_argument("--fleet_dir", type=str, default=None,
                   help="fleet telemetry: workers ship metric/event "
                        "snapshots over the launcher store; node 0 "
                        "aggregates them (counters summed, gauges "
                        "rank-labeled, straggler detection) into "
                        "fleet_metrics.json + a merged clock-aligned "
                        "fleet_trace.json in this directory")
    p.add_argument("--metrics_dump", type=str, default=None,
                   help="base PADDLE_TPU_METRICS_DUMP path; each worker "
                        "writes <base>.rank<N>.json (an inherited env "
                        "path is rewritten the same way)")
    p.add_argument("--chaos_kill_rank", type=int, default=None,
                   help="fault injection: the worker with this global "
                        "rank SIGKILLs itself ...")
    p.add_argument("--chaos_kill_step", type=int, default=None,
                   help="... after completing this training step "
                        "(requires a run_elastic training loop; see "
                        "tools/chaos_launch.py)")
    p.add_argument("--chaos_slow_rank", type=int, default=None,
                   help="straggler injection: this worker rank sleeps "
                        "--chaos_slow_seconds inside every step region "
                        "(fleet-telemetry drill)")
    p.add_argument("--chaos_slow_seconds", type=float, default=None,
                   help="extra host-side seconds per step for the slow "
                        "rank")
    p.add_argument("--chaos_creep_rank", type=int, default=None,
                   help="creeping-slowdown injection: this worker rank "
                        "gets --chaos_creep_pct percent of the base "
                        "sleep SLOWER each step (health-monitor drill; "
                        "see tools/chaos_launch.py --creep_rank)")
    p.add_argument("--chaos_creep_pct", type=float, default=None,
                   help="per-step slowdown growth, percent of the base "
                        "sleep (PADDLE_TPU_CHAOS_CREEP_BASE, default "
                        "0.05s)")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="accepted for parity; chips are mesh-addressed")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="accepted for parity; one proc drives all chips")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    a = p.parse_args()

    if a.chaos_kill_rank is not None and a.chaos_kill_step is not None:
        # workers inherit the controller env; elastic_train reads these
        os.environ["PADDLE_TPU_CHAOS_KILL_RANK"] = str(a.chaos_kill_rank)
        os.environ["PADDLE_TPU_CHAOS_KILL_STEP"] = str(a.chaos_kill_step)
        os.environ.setdefault("PADDLE_TPU_CHAOS_KILL_GEN", "0")
    if a.chaos_slow_rank is not None and a.chaos_slow_seconds is not None:
        os.environ["PADDLE_TPU_CHAOS_SLOW_RANK"] = str(a.chaos_slow_rank)
        os.environ["PADDLE_TPU_CHAOS_SLOW_SECONDS"] = \
            str(a.chaos_slow_seconds)
    if a.chaos_creep_rank is not None and a.chaos_creep_pct is not None:
        os.environ["PADDLE_TPU_CHAOS_CREEP_RANK"] = \
            str(a.chaos_creep_rank)
        os.environ["PADDLE_TPU_CHAOS_CREEP_PCT"] = str(a.chaos_creep_pct)

    if ":" in a.nnodes:
        # elastic mode: supervise relaunches within the np range.
        # Port layout: master port X = elastic supervisor store; X+4 =
        # launcher rendezvous (from which _build_pod derives X+6 for the
        # trainer store and X+7 for the jax coordinator) — the supervisor
        # and the inner controller must not fight over one port.
        from ..elastic import ElasticManager
        from ..store import create_store

        lo = int(a.nnodes.split(":")[0])
        store = create_store(a.master, a.node_rank, max(lo, 2))
        mgr = ElasticManager(store, node_id=str(a.node_rank),
                             np_range=a.nnodes, job_id=a.job_id)
        mgr.register()
        host, port = a.master.rsplit(":", 1)
        inner_master = f"{host}:{int(port) + 4}"

        def launcher_fn(rank_map):
            rank = rank_map.get(str(a.node_rank), a.node_rank)
            return launch(a.training_script, a.training_script_args,
                          len(rank_map), rank, inner_master, a.log_dir,
                          a.max_restarts, a.job_id, a.flight_dir,
                          a.fleet_dir, a.metrics_dump)

        status = mgr.watch(launcher_fn)
        sys.exit(0 if status == "completed" else 1)

    sys.exit(
        launch(a.training_script, a.training_script_args, int(a.nnodes),
               a.node_rank, a.master, a.log_dir, a.max_restarts, a.job_id,
               a.flight_dir, a.fleet_dir, a.metrics_dump)
    )


if __name__ == "__main__":
    main()
