"""Collective communication API.

Reference: python/paddle/distributed/communication/ (all_reduce.py,
all_gather.py, all_to_all.py, reduce_scatter.py, broadcast.py, scatter.py,
send/recv, stream variants) over ProcessGroupNCCL
(fluid/distributed/collective/process_group_nccl.cc).

TPU re-design — two execution contexts, one API:

1. **Inside shard_map capture** (fleet TP layers, custom kernels): the mesh
   axis is live, so ops lower directly to lax.psum / all_gather /
   ppermute / all_to_all — XLA schedules them on ICI.
2. **Eager on DistTensors**: the collective is expressed as a resharding of
   the global array (e.g. all_reduce of a Partial tensor → Replicate;
   all_gather of a Shard(i) tensor → Replicate) via jax.device_put, and XLA
   emits the collective program. A plain single-process tensor is its own
   world (world_size 1) → identity, matching the reference's behavior when
   the group has one rank.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ... import observability as _obs
from ...core.tensor import Tensor
from ..auto_parallel.placement import Partial, Replicate, Shard
from .group import (  # noqa: F401
    Group, destroy_process_group, get_backend, get_group, is_initialized,
    new_group,
)

# -- per-mesh collective telemetry (ROADMAP open item) -----------------------
# Every series is labeled by op + group: the group label is the mesh axis
# name when the Group wraps one (fleet tp/dp/pp axes), else "g<id>", else
# "world" — so a dump separates tp-axis allgather traffic from dp-axis
# allreduce traffic. tools/lint_registry.py rejects unlabeled series.
_obs_state = _obs.state
_M_COMM_CALLS = _obs.counter(
    "comm.collective_calls",
    "host-level collective API invocations, by op and group")
_M_COMM_BYTES = _obs.counter(
    "comm.collective_bytes",
    "input payload bytes moved through collectives, by op and group")
_M_COMM_SECONDS = _obs.histogram(
    "comm.collective_seconds",
    "host wall seconds inside a collective call (eager ops include the "
    "device work; in-trace ops only the capture cost), by op and group")


def _group_label(group) -> str:
    if group is None:
        return "world"
    axis = getattr(group, "axis_name", None)
    return axis if axis else f"g{group.id}"


def _payload_bytes(obj) -> int:
    """Byte size of a collective's input payload: Tensors (eager or
    tracer — avals still carry shape/dtype) and lists thereof; 0 for
    anything unsized."""
    try:
        if isinstance(obj, (list, tuple)):
            return sum(_payload_bytes(t) for t in obj)
        v = obj._value if isinstance(obj, Tensor) else obj
        aval = getattr(v, "aval", v)
        import numpy as _np

        return int(_np.prod(aval.shape)) * _np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _record_collective(op: str, group, nbytes: int, seconds: float):
    labels = {"op": op, "group": _group_label(group)}
    _M_COMM_CALLS.inc(**labels)
    if nbytes:
        _M_COMM_BYTES.inc(nbytes, **labels)
    _M_COMM_SECONDS.observe(seconds, **labels)
    _obs.emit("comm.collective", seconds=seconds, bytes=nbytes, **labels)


def _instrumented(op: str, payload_arg: int = 0):
    """Wrap a collective so that, with observability on, each call records
    calls/bytes/seconds labeled op+group. ``payload_arg`` indexes the
    positional argument whose bytes count as the payload. Disabled path:
    one attribute load and a truth test."""
    def deco(fn):
        import functools
        import inspect
        import time as _time

        try:
            payload_name = list(inspect.signature(fn).parameters)[payload_arg]
        except Exception:
            payload_name = None

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _obs_state.on:
                return fn(*args, **kwargs)
            group = kwargs.get("group")
            if group is None:
                for a in args:
                    if isinstance(a, Group):
                        group = a
                        break
            payload = (args[payload_arg] if len(args) > payload_arg
                       else kwargs.get(payload_name))
            nbytes = _payload_bytes(payload)
            t0 = _time.perf_counter()
            out = fn(*args, **kwargs)
            _record_collective(op, group, nbytes,
                               _time.perf_counter() - t0)
            return out

        wrapper.__wrapped__ = fn
        return wrapper

    return deco

__all__ = [
    "all_reduce", "all_gather", "all_gather_object", "all_to_all",
    "all_to_all_single", "reduce_scatter", "broadcast", "reduce", "scatter",
    "gather", "send", "recv", "isend", "irecv", "batch_isend_irecv",
    "P2POp", "ReduceOp", "new_group", "get_group", "wait", "barrier",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _reduce_fn(op):
    return {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
        ReduceOp.AVG: lambda x, n: jax.lax.pmean(x, n),
        # no pprod primitive: gather the axis then reduce locally
        ReduceOp.PROD: lambda x, n: jnp.prod(
            jax.lax.all_gather(x, n), axis=0),
    }[op]


def _is_tracer(t: Tensor):
    return isinstance(t._value, jax.core.Tracer)


def _live_world() -> int:
    """Process count of an initialized multi-process world, else 1."""
    from .. import env

    if env.is_initialized():
        try:
            return jax.process_count()
        except Exception:
            return 1
    return 1


def _process_allgather(value):
    """Gather a host-local array across all processes -> [world, ...].

    The cross-process analog of ProcessGroupNCCL allgather: lowers to an
    XLA collective over the global device mesh (gloo on CPU rigs, ICI/DCN
    on TPU pods)."""
    import numpy as np
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(value)))


@_instrumented("all_reduce")
def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op=True):
    """paddle.distributed.all_reduce parity (communication/all_reduce.py).
    In-place on ``tensor``."""
    if _is_tracer(tensor):
        # inside shard_map capture: reduce over the group's mesh axis
        axis = group.axis_name if group is not None and group.axis_name else None
        if axis is not None:
            tensor._replace_value(_reduce_fn(op)(tensor._value, axis))
        return tensor
    if tensor._dist_attr is not None:
        mesh, placements = tensor._dist_attr
        if any(isinstance(p, Partial) for p in placements):
            new_pl = [
                Replicate() if isinstance(p, Partial) else p for p in placements
            ]
            from ..auto_parallel.api import reshard

            out = reshard(tensor, mesh, new_pl)
            tensor._replace_value(out._value)
            tensor._dist_attr = out._dist_attr
        return tensor
    if _live_world() > 1:
        # plain tensor in a real multi-process world: gather + local reduce
        gathered = _process_allgather(tensor._value)
        if op == ReduceOp.SUM:
            red = gathered.sum(0)
        elif op == ReduceOp.MAX:
            red = gathered.max(0)
        elif op == ReduceOp.MIN:
            red = gathered.min(0)
        elif op == ReduceOp.PROD:
            red = gathered.prod(0)
        else:  # AVG
            red = gathered.mean(0)
        tensor._replace_value(jnp.asarray(red.astype(
            jnp.dtype(tensor._value.dtype))))
        return tensor
    # single-rank world: identity
    return tensor


@_instrumented("all_gather", payload_arg=1)
def all_gather(tensor_list: List[Tensor], tensor: Tensor,
               group: Optional[Group] = None, sync_op=True):
    """paddle.distributed.all_gather parity: fills tensor_list with each
    rank's shard. Eager DistTensor: unshard then split."""
    if tensor._dist_attr is not None:
        mesh, placements = tensor._dist_attr
        from ..auto_parallel.api import unshard_dtensor

        full = unshard_dtensor(tensor)
        # split along the sharded dim per mesh axis of the group
        shard_dims = [p.get_dim() for p in placements if isinstance(p, Shard)]
        n = group.nranks if group else (
            mesh.shape[0] if mesh.ndim else 1
        )
        if shard_dims:
            parts = jnp.split(full._value, n, axis=shard_dims[0])
        else:
            parts = [full._value for _ in range(n)]
        tensor_list.clear()
        tensor_list.extend(Tensor._from_value(p) for p in parts)
        return tensor_list
    if _live_world() > 1:
        gathered = _process_allgather(tensor._value)
        tensor_list.clear()
        tensor_list.extend(Tensor._from_value(jnp.asarray(g))
                           for g in gathered)
        return tensor_list
    tensor_list.clear()
    tensor_list.append(tensor.clone())
    return tensor_list


@_instrumented("all_gather_object", payload_arg=1)
def all_gather_object(object_list: List, obj, group=None):
    if _live_world() > 1:
        object_list.clear()
        object_list.extend(_object_allgather(obj))
        return object_list
    object_list.clear()
    object_list.append(obj)
    return object_list


@_instrumented("reduce_scatter", payload_arg=1)
def reduce_scatter(tensor: Tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    """Multi-process: all_reduce the concatenated input, keep this rank's
    chunk. Single-process: concat-and-keep-local-shard."""
    if isinstance(tensor_or_tensor_list, (list, tuple)):
        from ...ops.manipulation import concat

        src = concat(list(tensor_or_tensor_list), axis=0)
    else:
        src = tensor_or_tensor_list
    world = _live_world()
    if world > 1:
        reduced = Tensor._from_value(src._value)
        # bypass all_reduce's instrumentation: this call is the transport
        # of the reduce_scatter already recorded by our own wrapper, not a
        # second user-visible collective
        all_reduce.__wrapped__(reduced, op=op)
        me = jax.process_index()
        n = tensor._value.shape[0]
        tensor._replace_value(reduced._value[me * n:(me + 1) * n])
        return tensor
    tensor._replace_value(src._value[: tensor._value.shape[0]])
    return tensor


@_instrumented("all_to_all", payload_arg=1)
def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """out[j] on rank r = rank j's in[r]. Multi-process: gather every
    rank's input stack, pick this rank's column. Single-process world:
    identity permutation."""
    world = _live_world()
    if world > 1:
        import numpy as np

        stacked = np.stack([np.asarray(t._value) for t in in_tensor_list])
        gathered = _process_allgather(stacked)     # [world, world, ...]
        me = jax.process_index()
        out_tensor_list.clear()
        out_tensor_list.extend(
            Tensor._from_value(jnp.asarray(gathered[j, me]))
            for j in range(world))
        return out_tensor_list
    out_tensor_list.clear()
    out_tensor_list.extend(t.clone() for t in in_tensor_list)
    return out_tensor_list


@_instrumented("all_to_all_single", payload_arg=1)
def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    world = _live_world()
    if world > 1:
        if out_split_sizes is not None or in_split_sizes is not None:
            from ..utils.moe_utils import _check_single_rank

            _check_single_rank(group, "all_to_all_single(split_sizes)")
        import numpy as np

        gathered = _process_allgather(np.asarray(in_tensor._value))
        me = jax.process_index()
        chunk = in_tensor._value.shape[0] // world
        parts = [gathered[j, me * chunk:(me + 1) * chunk]
                 for j in range(world)]
        out_tensor._replace_value(jnp.asarray(np.concatenate(parts, 0)))
        return out_tensor
    out_tensor._replace_value(in_tensor._value)
    return out_tensor


@_instrumented("broadcast")
def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    if _is_tracer(tensor) or tensor._dist_attr is not None:
        return tensor
    if _live_world() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        out = multihost_utils.broadcast_one_to_all(
            np.asarray(tensor._value), is_source=jax.process_index() == src)
        tensor._replace_value(jnp.asarray(np.asarray(out)))
    return tensor


def _object_allgather(obj):
    """Pickle -> padded uint8 allgather -> unpickle per rank."""
    import pickle

    import numpy as np

    payload = np.frombuffer(
        pickle.dumps(obj, pickle.HIGHEST_PROTOCOL), dtype=np.uint8)
    n = np.array([payload.size], np.int64)
    sizes = _process_allgather(n)[:, 0]
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[: payload.size] = payload
    gathered = _process_allgather(buf)
    return [pickle.loads(gathered[r, : int(sizes[r])].tobytes())
            for r in range(gathered.shape[0])]


@_instrumented("broadcast_object_list")
def broadcast_object_list(object_list, src=0, group=None):
    if _live_world() > 1:
        objs = _object_allgather(list(object_list))[src]
        object_list[:] = objs
    return object_list


@_instrumented("reduce")
def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op=True):
    return all_reduce.__wrapped__(tensor, op, group)


@_instrumented("scatter", payload_arg=1)
def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    world = _live_world()
    if world > 1:
        import numpy as np

        payload = ([np.asarray(t._value) for t in tensor_list]
                   if tensor_list else None)
        parts = _object_allgather(payload)[src]
        tensor._replace_value(jnp.asarray(parts[jax.process_index()]))
        return tensor
    if tensor_list:
        tensor._replace_value(tensor_list[0]._value)
    return tensor


@_instrumented("gather")
def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    world = _live_world()
    if world > 1:
        import numpy as np

        all_vals = _object_allgather(np.asarray(tensor._value))
        if gather_list is not None and jax.process_index() == dst:
            gather_list.extend(Tensor._from_value(jnp.asarray(v))
                               for v in all_vals)
        return gather_list
    if gather_list is not None:
        gather_list.append(tensor.clone())
    return gather_list


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across hosts uses the pipeline-parallel "
        "ppermute path (fleet.meta_parallel) on TPU, not raw send/recv"
    )


recv = send
isend = send
irecv = send


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer


def batch_isend_irecv(p2p_op_list):
    raise NotImplementedError("see send/recv note")


class _Task:
    def wait(self):
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if not isinstance(tensor._value, jax.core.Tracer):
        jax.block_until_ready(tensor._value)


@_instrumented("barrier")
def barrier(group=None):
    from .. import env

    env.barrier(group)


# in-trace collective helpers for shard_map code (fleet layers use these)
def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def all_gather_in_trace(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all_in_trace(x, axis_name, split_axis, concat_axis):
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


from . import stream  # noqa: E402,F401
