"""paddle.distributed.communication.stream — stream-variant collectives.

Reference: python/paddle/distributed/communication/stream/ (each op with
sync_op/use_calc_stream knobs over ProcessGroup tasks). On TPU, XLA owns
stream scheduling, so these forward to the same collective
implementations; sync_op/use_calc_stream are accepted for parity and the
returned "task" is the tensor itself (already ordered by data deps).
"""
from __future__ import annotations

from .. import (  # noqa: F401
    all_gather, all_reduce, broadcast, gather, reduce, reduce_scatter,
    recv, scatter, send,
)
from .. import all_to_all as alltoall  # noqa: F401
from .. import all_to_all_single as alltoall_single  # noqa: F401

__all__ = [
    "all_gather", "all_reduce", "alltoall", "alltoall_single", "broadcast",
    "reduce", "reduce_scatter", "recv", "scatter", "send", "gather",
]
