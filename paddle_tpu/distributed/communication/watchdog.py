"""Async communication watchdog.

Reference: paddle/phi/core/distributed/comm_task_manager.h
(CommTaskManager + NCCLCommTask — tracks async collective status and
flags hangs/timeouts; FLAGS_stop_check_timeout read at parallel.py:1133).

TPU re-design: XLA collectives complete inside compiled programs, so the
hang surface moves to HOST-side coordination — store rendezvous,
barriers, cross-host data waits. CommTaskManager watches those: register
a task around any blocking wait; a daemon thread flags tasks that
outlive their timeout (warn, then abort like the reference's
FLAGS_stop_check_timeout behavior).
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Dict, Optional

from ... import observability as _obs
from ...core import flags

__all__ = ["CommTask", "CommTaskManager", "get_comm_task_manager"]

# overdue tasks surface as metrics (not just log lines): comm.task_overdue
# is the alert series an operator watches, comm.task_seconds the latency
# distribution of every registered wait. An overdue task also writes the
# flight-recorder post-mortem — a watchdog timeout IS the multi-chip
# "job died" moment the recorder exists for.
_M_TASKS = _obs.counter(
    "comm.tasks_started",
    "communication/coordination waits registered with the watchdog, by "
    "task name")
_M_TASK_SECONDS = _obs.histogram(
    "comm.task_seconds",
    "wall seconds a registered comm task stayed in flight, by task name")
_M_TASK_OVERDUE = _obs.counter(
    "comm.task_overdue",
    "watchdog detections of a task outliving its timeout (each task "
    "counts once), by task name")
_M_SCANS = _obs.counter(
    "comm.watchdog_scans",
    "watchdog scan-loop passes over the in-flight task table")


class CommTask:
    """One in-flight communication/coordination op."""

    __slots__ = ("name", "start_s", "timeout_s", "done", "warned")

    def __init__(self, name: str, timeout_s: float):
        self.name = name
        self.start_s = time.time()
        self.timeout_s = timeout_s
        self.done = False
        self.warned = False

    def elapsed_s(self) -> float:
        return time.time() - self.start_s

    def overdue(self) -> bool:
        return not self.done and self.elapsed_s() > self.timeout_s


class CommTaskManager:
    """Reference: comm_task_manager.h:?? CommTaskManager — a loop thread
    scanning in-flight tasks."""

    def __init__(self, scan_interval_s: float = 1.0):
        self._tasks: Dict[int, CommTask] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._scan_interval_s = scan_interval_s
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._overdue_log = []

    # -- task lifecycle --------------------------------------------------
    def start_task(self, name: str, timeout_s: Optional[float] = None) -> int:
        if timeout_s is None:
            timeout_s = float(flags.get_flag("stop_check_timeout"))
        task = CommTask(name, timeout_s)
        with self._lock:
            self._seq += 1
            tid = self._seq
            self._tasks[tid] = task
        if _obs.state.on:
            _M_TASKS.inc(name=name)
        self._ensure_thread()
        return tid

    def end_task(self, tid: int):
        with self._lock:
            task = self._tasks.pop(tid, None)
            if task is not None:
                task.done = True
        if task is not None and _obs.state.on:
            _M_TASK_SECONDS.observe(task.elapsed_s(), name=task.name)

    def task(self, name: str, timeout_s: Optional[float] = None):
        """Context manager form: with manager.task('barrier'): ..."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            tid = self.start_task(name, timeout_s)
            try:
                yield
            finally:
                self.end_task(tid)

        return cm()

    # -- watchdog loop ---------------------------------------------------
    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._scan_interval_s):
            with self._lock:
                tasks = list(self._tasks.values())
            if _obs.state.on:
                _M_SCANS.inc()
            if not tasks:
                continue
            for t in tasks:
                if t.overdue() and not t.warned:
                    t.warned = True
                    msg = (f"CommTaskManager: task '{t.name}' has been "
                           f"in-flight for {t.elapsed_s():.0f}s "
                           f"(timeout {t.timeout_s:.0f}s) — probable "
                           f"distributed hang")
                    self._overdue_log.append(msg)
                    # warn before flipping the metric: pollers treat
                    # comm.task_overdue as "the alert already happened"
                    warnings.warn(msg)
                    if _obs.state.on:
                        _obs.emit("comm.task_overdue", name=t.name,
                                  elapsed_s=round(t.elapsed_s(), 3),
                                  timeout_s=t.timeout_s)
                        # inc before the dump so the post-mortem's metric
                        # snapshot shows the overdue counter that fired it
                        _M_TASK_OVERDUE.inc(name=t.name)
                        # the post-mortem moment: a distributed wait blew
                        # its deadline, dump the flight ring while the
                        # process is still alive to write it
                        _obs.flight.recorder.dump(
                            "watchdog_timeout",
                            TimeoutError(msg),
                            context={"task": t.name,
                                     "elapsed_s": round(t.elapsed_s(), 3),
                                     "timeout_s": t.timeout_s})

    def overdue_tasks(self):
        with self._lock:
            return [t for t in self._tasks.values() if t.overdue()]

    def overdue_log(self):
        return list(self._overdue_log)

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


_manager: Optional[CommTaskManager] = None
_manager_lock = threading.Lock()


def get_comm_task_manager() -> CommTaskManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = CommTaskManager()
        return _manager
