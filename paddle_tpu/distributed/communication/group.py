"""Communication groups.

Reference: python/paddle/distributed/communication/group.py (Group,
new_group) + C++ CommContextManager keyed contexts.

TPU re-design: a Group names a subset of devices — usually one axis of a
ProcessMesh — and collectives over it become XLA collectives along that
axis. Group state is lightweight python; there is no NCCL communicator to
initialize (ICI routes are wired by XLA at compile time).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax


class Group:
    def __init__(self, rank_in_group: int, group_id: int, ranks: List[int],
                 mesh=None, axis_name: Optional[str] = None):
        self._rank_in_group = rank_in_group
        self._id = group_id
        self._ranks = list(ranks)
        self.mesh = mesh  # ProcessMesh this group is an axis of (if any)
        self.axis_name = axis_name

    @property
    def rank(self) -> int:
        return self._rank_in_group

    @property
    def ranks(self) -> List[int]:
        return self._ranks

    @property
    def nranks(self) -> int:
        return len(self._ranks)

    world_size = nranks

    @property
    def id(self) -> int:
        return self._id

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank: int) -> int:
        return self._ranks.index(rank) if rank in self._ranks else -1

    def is_member(self) -> bool:
        from .. import env

        return env.get_rank() in self._ranks or True

    def __repr__(self):
        return f"Group(id={self._id}, ranks={self._ranks}, axis={self.axis_name})"


_groups: dict = {}
_group_counter = [0]


def _get_or_create_default_group() -> Group:
    if 0 not in _groups:
        n = max(len(jax.devices()), 1)
        _groups[0] = Group(0, 0, list(range(n)))
    return _groups[0]


def new_group(ranks: Optional[Sequence[int]] = None, backend=None,
              timeout=None) -> Group:
    """paddle.distributed.new_group parity. On TPU this is bookkeeping only —
    no communicator handshake (reference does ncclCommInitRank here)."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    g = Group(0, gid, list(ranks))
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    return _groups.get(gid) or _get_or_create_default_group()


def axis_group(mesh, axis_name: str) -> Group:
    """Group representing one mesh axis (the HybridCommunicateGroup path)."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    axis = mesh.dim_names.index(axis_name)
    g = Group(0, gid, list(range(mesh.shape[axis])), mesh=mesh, axis_name=axis_name)
    _groups[gid] = g
    return g


def is_initialized() -> bool:
    from .. import env

    return env.is_initialized()


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


def get_backend(group=None) -> str:
    return "xla"
