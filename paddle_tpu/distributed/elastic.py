"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py
(ElasticManager :124 — registers nodes in etcd :217-238, watches
membership, scales np within --np min:max, relaunches the job with
re-ranked endpoints via LauncherInterface :56; status enum ElasticStatus
:48) and the CLI glue distributed/elastic.py.

TPU re-design: etcd is replaced by the native TCPStore (the rendezvous
service the launcher already runs); membership is a heartbeat key per
node with a TTL the manager enforces by timestamp. Recovery stays
"relaunch + checkpoint-resume", same as the reference (§5.3): no
in-process peer repair is attempted.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from .. import observability as _obs
from .store import Store

__all__ = ["ElasticStatus", "ElasticManager", "PeerMonitor"]

# The elastic. subsystem (claimed in observability.metrics
# CLAIMED_SUBSYSTEMS): the recovery-cost counters the ROADMAP item-1
# acceptance reads back out of bench.py --metrics / obs.dump().
M_RESTARTS = _obs.counter(
    "elastic.restarts",
    "worker rejoins at a bumped generation, by trigger reason "
    "(relaunch = coordinated whole-world restart)")
M_PEER_DEATHS = _obs.counter(
    "elastic.peer_deaths",
    "stale-heartbeat peer deaths this worker detected, by peer node id")
M_RERENDEZVOUS_SECONDS = _obs.histogram(
    "elastic.rerendezvous_seconds",
    "wall seconds a rejoining worker spent re-forming the world "
    "(store rendezvous + jax.distributed bring-up) after a restart")
M_STEPS_LOST = _obs.counter(
    "elastic.steps_lost",
    "training steps that had run past the restored checkpoint and were "
    "re-executed after an elastic resume")
M_RESTORE_SECONDS = _obs.histogram(
    "elastic.checkpoint_restore_seconds",
    "wall seconds an elastic resume spent loading the latest checkpoint")
M_SAVE_SECONDS = _obs.histogram(
    "elastic.checkpoint_save_seconds",
    "wall seconds each periodic elastic checkpoint spent from async "
    "kickoff to durable (writer joined)")


class ElasticStatus:
    """Reference: manager.py:48."""
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Membership + scale watcher over the rendezvous store.

    np_range "min:max" (reference --np syntax). The manager:
    - heartbeats this node's liveness key,
    - watches the member set,
    - reports RESTART when membership changed but stays within range
      (job relaunches with re-ranked nodes),
    - reports HOLD while below min (waiting for nodes),
    - reports COMPLETED/ERROR from the job's own exit.
    """

    def __init__(self, store: Store, node_id: str, np_range: str = "1:1",
                 job_id: str = "default", heartbeat_interval_s: float = 2.0,
                 dead_after_s: float = 10.0):
        self.store = store
        self.node_id = node_id
        self.job_id = job_id
        lo, _, hi = np_range.partition(":")
        self.np_min = int(lo)
        self.np_max = int(hi or lo)
        self.heartbeat_interval_s = heartbeat_interval_s
        self.dead_after_s = dead_after_s
        self._registered = False
        self._last_members: Optional[List[str]] = None

    # -- membership (reference: manager.py:217-238 etcd registration) ----
    # Membership is an atomic append log: register claims a slot index via
    # the store's atomic add() and writes member/<idx> = node_id; readers
    # scan member/0..seq. No read-modify-write of a shared list, so
    # concurrent registrations (the normal elastic startup) cannot lose
    # members. Deregistration tombstones the slot.
    def _seq_key(self):
        return f"elastic/{self.job_id}/member_seq"

    def _slot_key(self, idx: int):
        return f"elastic/{self.job_id}/member/{idx}"

    def _node_key(self, node_id: str):
        return f"elastic/{self.job_id}/node/{node_id}"

    def register(self):
        """Join the member set and start counting as alive."""
        if self.node_id not in self._read_members():
            idx = self.store.add(self._seq_key(), 1) - 1
            self.store.set(self._slot_key(idx), self.node_id)
        self.heartbeat()
        self._registered = True

    def deregister(self):
        for idx in range(self._seq_len()):
            try:
                raw = self.store.get(self._slot_key(idx), timeout_s=0)
            except Exception:
                continue
            if raw.decode() == self.node_id:
                self.store.set(self._slot_key(idx), "")  # tombstone
        self._registered = False

    def heartbeat(self):
        self.store.set(self._node_key(self.node_id), str(time.time()))

    def _seq_len(self) -> int:
        try:
            return int(self.store.get(self._seq_key(), timeout_s=0))
        except Exception:
            return 0

    def _read_members(self) -> List[str]:
        members = []
        for idx in range(self._seq_len()):
            try:
                raw = self.store.get(self._slot_key(idx), timeout_s=0)
            except Exception:
                continue
            name = raw.decode()
            if name and name not in members:
                members.append(name)
        return members

    def alive_members(self) -> List[str]:
        """Members whose heartbeat is fresher than dead_after_s."""
        now = time.time()
        alive = []
        for m in self._read_members():
            try:
                ts = float(self.store.get(self._node_key(m), timeout_s=0))
            except Exception:
                continue
            if now - ts <= self.dead_after_s:
                alive.append(m)
        return alive

    def dead_members(self) -> List[str]:
        """Members that DID register and heartbeat at least once but whose
        heartbeat is now staler than dead_after_s — the positive death
        signal (a member with no heartbeat key yet is merely *joining*,
        not dead)."""
        now = time.time()
        dead = []
        for m in self._read_members():
            try:
                ts = float(self.store.get(self._node_key(m), timeout_s=0))
            except Exception:
                continue
            if now - ts > self.dead_after_s:
                dead.append(m)
        return dead

    # -- generation (reference: the launcher's coordinated-restart
    # counter; surfaced here so elastic clients and unit tests share one
    # implementation with CollectiveController) -------------------------
    def _generation_key(self):
        return f"elastic/{self.job_id}/generation"

    def generation(self) -> int:
        """Current re-rendezvous generation (0 until the first restart)."""
        try:
            return int(self.store.get(self._generation_key(), timeout_s=0))
        except Exception:
            return 0

    def bump_generation(self) -> int:
        """Atomically advance the generation — every member observing a
        value above its own must drop its world and re-rendezvous."""
        return self.store.add(self._generation_key(), 1)

    # -- scale decisions (reference: manager.py watch loop) --------------
    def check_scale(self) -> str:
        """One watch-loop tick: HOLD below min, RESTART on membership
        change within range, ERROR above max (misconfiguration)."""
        alive = self.alive_members()
        n = len(alive)
        if n < self.np_min:
            return ElasticStatus.HOLD
        if n > self.np_max:
            return ElasticStatus.ERROR
        if self._last_members is None:
            self._last_members = sorted(alive)
            return "ok"
        if sorted(alive) != self._last_members:
            self._last_members = sorted(alive)
            return ElasticStatus.RESTART
        return "ok"

    def rerank(self) -> Dict[str, int]:
        """New node_id → rank map after a membership change (the
        reference re-writes trainer endpoints the same way)."""
        return {m: i for i, m in enumerate(sorted(self.alive_members()))}

    # -- supervised run (reference: LauncherInterface :56) ---------------
    def watch(self, launcher_fn: Callable[[Dict[str, int]], int],
              poll_interval_s: float = 1.0,
              max_relaunches: int = 10) -> str:
        """Run launcher_fn under elastic supervision. launcher_fn receives
        the current rank map and returns the job's exit code; the manager
        relaunches on membership change until the job completes.

        A background thread keeps heartbeating while launcher_fn blocks —
        otherwise every node would look dead to its peers for the whole
        job duration and a single worker failure would split-brain the
        membership."""
        import threading

        stop = threading.Event()

        def beat():
            while not stop.is_set():
                try:
                    self.heartbeat()
                except Exception:
                    pass
                stop.wait(self.heartbeat_interval_s)

        beater = threading.Thread(target=beat, daemon=True)
        beater.start()
        try:
            relaunches = 0
            while True:
                status = self.check_scale()
                if status == ElasticStatus.HOLD:
                    time.sleep(poll_interval_s)
                    continue
                if status == ElasticStatus.ERROR:
                    return ElasticStatus.ERROR
                rc = launcher_fn(self.rerank())
                if rc == 0:
                    return ElasticStatus.COMPLETED
                relaunches += 1
                if relaunches > max_relaunches:
                    return ElasticStatus.ERROR
                # refresh membership before relaunching
                self._last_members = None
                time.sleep(poll_interval_s)
        finally:
            stop.set()
            beater.join(timeout=2)


class PeerMonitor:
    """In-worker heartbeat + peer-death watch over an ElasticManager.

    Two jobs, one daemon thread:
    - keep THIS worker's heartbeat fresh while the training loop blocks
      in compiled steps / collectives;
    - watch every expected peer's heartbeat and fire ``on_death(peer)``
      the moment one goes stale — the survivor's escape hatch out of a
      collective that will never complete (the dead peer can't join it).

    A peer is reported dead when its store heartbeat is staler than the
    manager's ``dead_after_s``, or when the store itself has been
    unreachable for that long (the store server rides a worker process,
    so losing it IS a peer death). ``on_death`` runs on the monitor
    thread: it must be async-signal-ish safe — dump state and exit, don't
    try to repair the world in-process (recovery is relaunch+resume,
    reference §5.3).
    """

    def __init__(self, manager: ElasticManager, expected: List[str],
                 on_death: Callable[[str], None],
                 poll_interval_s: float = 0.5):
        self.manager = manager
        self.expected = [str(p) for p in expected
                         if str(p) != manager.node_id]
        self.on_death = on_death
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fired = False
        # local receive clock per peer: covers both a stale stored
        # timestamp AND an unreadable store (peer clock skew can't fake
        # liveness, store loss can't mask death)
        self._last_seen = {p: time.time() for p in self.expected}

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="ptpu-elastic-peer-monitor",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self):
        mgr = self.manager
        while not self._stop.wait(self.poll_interval_s):
            try:
                mgr.heartbeat()
            except Exception:
                pass  # store loss shows up through the read side below
            now = time.time()
            dead = None
            for peer in self.expected:
                try:
                    ts = float(mgr.store.get(mgr._node_key(peer),
                                             timeout_s=0))
                except Exception:
                    ts = None
                if ts is not None and now - ts <= mgr.dead_after_s:
                    self._last_seen[peer] = now
                    continue
                if now - self._last_seen[peer] > mgr.dead_after_s:
                    dead = peer
                    break
            if dead is not None and not self._fired:
                self._fired = True
                M_PEER_DEATHS.inc(peer=dead)
                try:
                    self.on_death(dead)
                finally:
                    return
