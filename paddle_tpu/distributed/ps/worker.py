"""Worker-side PS integration.

Reference: the PS worker path — sparse_embedding lookups through the PS
(python/paddle/static/nn sparse_embedding + distributed_ops) and the
DistributedStrategy a_sync / sync training loop (fleet/fleet.py:892-936,
distributed/ps/the_one_ps.py).

DistributedEmbedding pulls rows for each batch from the PS and pushes row
gradients in its custom backward; PsOptimizer pushes dense gradients and
pulls fresh parameters each step (async: immediately applied server-side;
sync: server waits for all trainers; geo: local steps with periodic delta
pushes)."""
from __future__ import annotations

import numpy as np

from ...autograd import PyLayer
from ...core.tensor import Tensor
from ...nn.layer import Layer
from .client import PsClient


class _PsEmbeddingFn(PyLayer):
    @staticmethod
    def forward(ctx, ids, client, table_id, emb_dim):
        ids_np = np.asarray(ids._value, "int64")
        rows = client.pull_sparse(table_id, ids_np.reshape(-1))
        ctx.save = (client, table_id, ids_np, rows.shape[-1])
        out = rows.reshape(ids_np.shape + (emb_dim,))
        return Tensor._from_value(np.asarray(out, "float32"))

    @staticmethod
    def backward(ctx, grad_out):
        client, table_id, ids_np, emb_dim = ctx.save
        g = np.asarray(grad_out._value, "float32").reshape(-1, emb_dim)
        client.push_sparse(table_id, ids_np.reshape(-1), g)
        return None  # ids take no gradient


class DistributedEmbedding(Layer):
    """Embedding whose table lives on the parameter servers."""

    def __init__(self, client: PsClient, table_id: int, emb_dim: int,
                 lr: float = 0.01, optimizer: str = "sgd",
                 init_range: float = 0.01, seed: int = 0):
        super().__init__()
        self.client = client
        self.table_id = int(table_id)
        self.emb_dim = int(emb_dim)
        client.init_sparse(self.table_id, emb_dim, lr=lr, optimizer=optimizer,
                           init_range=init_range, seed=seed)

    def forward(self, ids):
        return _PsEmbeddingFn.apply(ids, self.client, self.table_id, self.emb_dim)


class PsOptimizer:
    """Dense-parameter training against the PS (reference
    fleet.distributed_optimizer in PS mode).

    mode: "async" (push grad → server applies immediately → pull),
          "sync"  (server averages one grad per trainer per step),
          "geo"   (local optimizer steps; every ``geo_k`` steps push the
                   accumulated parameter delta with a "sum" server rule —
                   geo-SGD, reference ps/README + geo mode strategy).
    """

    def __init__(self, parameters, client: PsClient, lr=0.01, mode="async",
                 table_id_base=0, geo_k=4, local_optimizer=None):
        if mode not in ("async", "sync", "geo"):
            raise ValueError(f"unknown ps mode {mode}")
        if mode == "geo" and local_optimizer is None:
            raise ValueError(
                "mode='geo' requires a local_optimizer that applies the "
                "between-sync local steps"
            )
        self.params = list(parameters)
        self.client = client
        self.mode = mode
        self.geo_k = int(geo_k)
        self._step_count = 0
        self._local_opt = local_optimizer
        self.tables = {}
        self._geo_anchors = {}
        for i, p in enumerate(self.params):
            tid = table_id_base + i
            self.tables[id(p)] = tid
            init = np.asarray(p._value, "float32")
            client.init_dense(
                tid, init, lr=lr,
                optimizer="sum" if mode == "geo" else "sgd",
                sync=(mode == "sync"),
            )
            if mode == "geo":
                self._geo_anchors[id(p)] = init.copy()

    def step(self):
        self._step_count += 1
        if self.mode == "geo":
            # local update, periodic delta exchange
            self._local_opt.step()
            if self._step_count % self.geo_k == 0:
                for p in self.params:
                    tid = self.tables[id(p)]
                    cur = np.asarray(p._value, "float32")
                    delta = cur - self._geo_anchors[id(p)]
                    self.client.push_dense(tid, delta)
                    fresh = self.client.pull_dense(tid)
                    p._replace_value(fresh)
                    self._geo_anchors[id(p)] = fresh.copy()
            return
        for p in self.params:
            if p.grad is None:
                continue
            tid = self.tables[id(p)]
            self.client.push_dense(tid, np.asarray(p.grad._value, "float32"))
            p._replace_value(self.client.pull_dense(tid))

    def clear_grad(self):
        for p in self.params:
            p.clear_grad()
        if self._local_opt is not None:
            self._local_opt.clear_grad()
